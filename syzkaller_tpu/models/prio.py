"""Call-to-call priorities and the ChoiceTable.

Static component from shared argument types, dynamic component from
corpus co-occurrence, normalized to 0.1..1 and multiplied
(reference: prog/prio.go:27-187).  The ChoiceTable is a per-call
prefix-sum row sampled by binary search — exactly the matrix the TPU
engine uploads as its device-side categorical sampler
(reference: prog/prio.go:191-245; device side: ops/choice.py).
"""

from __future__ import annotations

import bisect
from typing import Optional

from syzkaller_tpu.models.prog import Prog
from syzkaller_tpu.models.types import (
    ArrayType,
    BufferKind,
    BufferType,
    PtrType,
    ResourceType,
    StructType,
    Syscall,
    UnionType,
    VmaType,
    foreach_type,
)


def calculate_priorities(target, corpus: list[Prog]) -> list[list[float]]:
    """static x dynamic (reference: prog/prio.go:27-36)."""
    static = calc_static_priorities(target)
    dynamic = calc_dynamic_prio(target, corpus)
    for i in range(len(static)):
        row_s, row_d = static[i], dynamic[i]
        for j in range(len(row_s)):
            row_d[j] *= row_s[j]
    return dynamic


def calc_static_priorities(target) -> list[list[float]]:
    """Shared-type usage weights (reference: prog/prio.go:38-131)."""
    uses: dict[str, dict[int, float]] = {}

    for c in target.syscalls:
        def note_usage(weight: float, id_: str) -> None:
            m = uses.setdefault(id_, {})
            if weight > m.get(c.id, 0.0):
                m[c.id] = weight

        def visit(t) -> None:
            if isinstance(t, ResourceType):
                assert t.desc is not None
                if t.desc.name in ("pid", "uid", "gid"):
                    # Aux roles that appear in masses of structs.
                    note_usage(0.1, f"res{t.desc.name}")
                else:
                    s = "res"
                    for i, k in enumerate(t.desc.kind):
                        s += "-" + k
                        w = 1.0 if i == len(t.desc.kind) - 1 else 0.2
                        note_usage(w, s)
            elif isinstance(t, PtrType):
                if isinstance(t.elem, (StructType, UnionType)):
                    note_usage(1.0, f"ptrto-{t.elem.name}")
                if isinstance(t.elem, ArrayType):
                    note_usage(1.0, f"ptrto-{t.elem.elem.name}")
            elif isinstance(t, BufferType):
                if t.kind == BufferKind.STRING:
                    if t.sub_kind:
                        note_usage(0.2, f"str-{t.sub_kind}")
                elif t.kind == BufferKind.FILENAME:
                    note_usage(1.0, "filename")
            elif isinstance(t, VmaType):
                note_usage(0.5, "vma")

        foreach_type(c, visit)

    n = len(target.syscalls)
    prios = [[0.0] * n for _ in range(n)]
    for calls in uses.values():
        for c0, w0 in calls.items():
            for c1, w1 in calls.items():
                if c0 == c1:
                    continue
                prios[c0][c1] += w0 * w1
    # Self-priority = max priority wrt others (reference: prio.go:120-128).
    for c0, pp in enumerate(prios):
        pp[c0] = max(pp)
    normalize_prio(prios)
    return prios


def calc_dynamic_prio(target, corpus: list[Prog]) -> list[list[float]]:
    """Corpus co-occurrence counts (reference: prog/prio.go:133-149)."""
    n = len(target.syscalls)
    prios = [[0.0] * n for _ in range(n)]
    for p in corpus:
        for c0 in p.calls:
            for c1 in p.calls:
                prios[c0.meta.id][c1.meta.id] += 1.0
    normalize_prio(prios)
    return prios


def normalize_prio(prios: list[list[float]]) -> None:
    """Per-row normalize to 0.1..1, zeros get a sub-min floor
    (reference: prog/prio.go:153-187)."""
    for prio in prios:
        max_p = max(prio) if prio else 0.0
        nonzero = [p for p in prio if p != 0]
        min_p = min(nonzero) if nonzero else 1e10
        nzero = len(prio) - len(nonzero)
        if nzero != 0:
            min_p /= 2 * nzero
        for i, p in enumerate(prio):
            if max_p == 0:
                prio[i] = 1.0
                continue
            if p == 0:
                p = min_p
            if max_p == min_p:
                # Uniform nonzero row: everything is at the max
                # (the reference would produce NaN here; clamp to 1).
                prio[i] = 1.0
                continue
            p = (p - min_p) / (max_p - min_p) * 0.9 + 0.1
            prio[i] = min(p, 1.0)


class ChoiceTable:
    """Weighted next-call sampler (reference: prog/prio.go:191-245)."""

    def __init__(self, target, run: list[Optional[list[int]]],
                 enabled_calls: list[Syscall]):
        self.target = target
        self.run = run
        self.enabled_calls = enabled_calls
        self.enabled_ids = {c.id for c in enabled_calls}

    def enabled_by_id(self, call_id: int) -> bool:
        return call_id in self.enabled_ids

    def choose(self, rng, call: int) -> int:
        """Sample the next syscall id biased by `call`
        (reference: prog/prio.go:230-245)."""
        if call < 0:
            return self.enabled_calls[rng.intn(len(self.enabled_calls))].id
        run = self.run[call]
        if run is None:
            return self.enabled_calls[rng.intn(len(self.enabled_calls))].id
        while True:
            x = rng.intn(run[-1]) + 1
            i = bisect.bisect_left(run, x)
            if i in self.enabled_ids:
                return i


def build_choice_table(target, prios: Optional[list[list[float]]] = None,
                       enabled: Optional[dict[Syscall, bool]] = None) -> ChoiceTable:
    """(reference: prog/prio.go:198-228)"""
    if enabled is None:
        enabled = {c: True for c in target.syscalls}
    enabled_calls = [c for c in enabled if enabled[c]]
    enabled_ids = {c.id for c in enabled_calls}
    run: list[Optional[list[int]]] = [None] * len(target.syscalls)
    for i in range(len(target.syscalls)):
        if target.syscalls[i].id not in enabled_ids:
            continue
        row = [0] * len(target.syscalls)
        total = 0
        for j in range(len(target.syscalls)):
            if target.syscalls[j].id in enabled_ids:
                w = 1
                if prios is not None:
                    w = int(prios[i][j] * 1000)
                total += w
            row[j] = total
        run[i] = row
    return ChoiceTable(target, run, enabled_calls)
