"""Length-field assignment.

len/bytesize/bitsize args measure a sibling field, "parent", or a named
ancestor struct; values are recomputed after every structural edit
(reference: prog/size.go:11-117).
"""

from __future__ import annotations

from typing import Optional

from syzkaller_tpu.models.prog import (
    Arg,
    Call,
    ConstArg,
    GroupArg,
    PointerArg,
    foreach_sub_arg,
    inner_arg,
)
from syzkaller_tpu.models.types import (
    ArrayType,
    LenType,
    StructType,
    VmaType,
    is_pad,
)


def generate_size(arg: Optional[Arg], len_type: LenType) -> int:
    """Measured size of arg in len_type's units
    (reference: prog/size.go:11-34)."""
    if arg is None:
        # Optional pointer: size 0.
        return 0
    bit_size = len_type.bit_size or 8
    t = arg.typ
    if isinstance(t, VmaType):
        assert isinstance(arg, PointerArg)
        return arg.vma_size * 8 // bit_size
    if isinstance(t, ArrayType):
        assert isinstance(arg, GroupArg)
        if len_type.bit_size != 0:
            return arg.size() * 8 // bit_size
        return len(arg.inner)
    return arg.size() * 8 // bit_size


def _assign_sizes(args: list[Arg], parents: dict[int, Arg]) -> None:
    """(reference: prog/size.go:36-92)"""
    args_map: dict[str, Arg] = {}
    for arg in args:
        if is_pad(arg.typ):
            continue
        args_map[arg.typ.field_name] = arg

    for arg0 in args:
        arg = inner_arg(arg0)
        if arg is None:
            continue  # pointer to optional len field
        t = arg.typ
        if not isinstance(t, LenType):
            continue
        assert isinstance(arg, ConstArg)
        buf = args_map.get(t.buf)
        if buf is not None:
            arg.val = generate_size(inner_arg(buf), t)
            continue
        if t.buf == "parent":
            parent = parents.get(id(arg))
            assert parent is not None, f"no parent for len field {t.field_name}"
            arg.val = parent.size()
            if t.bit_size != 0:
                arg.val = arg.val * 8 // t.bit_size
            continue
        # Named ancestor struct (possibly a template instance "name[...]").
        assigned = False
        parent = parents.get(id(arg))
        while parent is not None:
            pname = parent.typ.name
            if "[" in pname:
                pname = pname[: pname.index("[")]
            if t.buf == pname:
                arg.val = parent.size()
                if t.bit_size != 0:
                    arg.val = arg.val * 8 // t.bit_size
                assigned = True
                break
            parent = parents.get(id(parent))
        if not assigned:
            raise ValueError(
                f"len field {t.field_name!r} references nonexistent field {t.buf!r}")


def assign_sizes_array(args: list[Arg]) -> None:
    """(reference: prog/size.go:94-113)"""
    parents: dict[int, Arg] = {}
    for arg in args:
        def note(a, ctx) -> None:
            if isinstance(a.typ, StructType):
                assert isinstance(a, GroupArg)
                for f in a.inner:
                    fi = inner_arg(f)
                    if fi is not None:
                        parents[id(fi)] = a

        foreach_sub_arg(arg, note)
    _assign_sizes(args, parents)
    for arg in args:
        def fix(a, ctx) -> None:
            if isinstance(a.typ, StructType):
                _assign_sizes(a.inner, parents)

        foreach_sub_arg(arg, fix)


def assign_sizes_call(c: Call) -> None:
    assign_sizes_array(c.args)


def mutate_size(rng, arg: ConstArg, parent: list[Arg]) -> bool:
    """Len-field mutation: small perturbations and overflow-provoking
    values scaled by element size (reference: prog/size.go:119-175)."""
    t = arg.typ
    assert isinstance(t, LenType)
    elem_size = t.bit_size // 8
    if elem_size == 0:
        elem_size = 1
        for field in parent:
            if t.buf != field.typ.field_name:
                continue
            inner = inner_arg(field)
            if inner is not None:
                it = inner.typ
                if isinstance(it, VmaType):
                    return False
                if isinstance(it, ArrayType):
                    assert it.elem is not None
                    if it.elem.varlen:
                        return False
                    elem_size = it.elem.size()
            break
    if rng.one_of(100):
        arg.val = rng.rand64()
        return True
    if rng.bin():
        # Small adjustment to trigger missed size checks.
        if arg.val != 0 and rng.bin():
            arg.val = rng.rand_range_int(0, arg.val - 1)
        else:
            arg.val = rng.rand_range_int(arg.val + 1, arg.val + 1000)
        arg.val &= (1 << 64) - 1
        return True
    # Try to provoke int overflows.
    maxv = (1 << 64) - 1
    if rng.one_of(3):
        maxv = (1 << 32) - 1
        if rng.one_of(2):
            maxv = (1 << 16) - 1
            if rng.one_of(2):
                maxv = (1 << 8) - 1
    n = maxv // elem_size
    delta = 1000 - rng.biased_rand(1000, 10)
    if elem_size == 1 or rng.one_of(10):
        n -= delta
    else:
        n += delta
    arg.val = n & ((1 << 64) - 1)
    return True
