"""Conservative program-state analysis.

Rebuilds the prefix state of a program (open files, live resources,
seen strings, mapped memory) by scanning calls, feeding generation and
mutation decisions (reference: prog/analysis.go:15-98,158-172).
"""

from __future__ import annotations

from typing import Optional

from syzkaller_tpu.models.alloc import MemAlloc, VmaAlloc
from syzkaller_tpu.models.prog import (
    Call,
    ConstArg,
    DataArg,
    PointerArg,
    Prog,
    ResultArg,
    foreach_arg,
)
from syzkaller_tpu.models.types import (
    BufferKind,
    BufferType,
    CsumType,
    Dir,
    ResourceType,
)


class State:
    """(reference: prog/analysis.go:15-49)"""

    def __init__(self, target, ct=None):
        self.target = target
        self.ct = ct  # ChoiceTable
        self.files: dict[str, bool] = {}
        self.resources: dict[str, list[ResultArg]] = {}
        self.strings: dict[str, bool] = {}
        self.ma = MemAlloc(target.num_pages * target.page_size)
        self.va = VmaAlloc(target.num_pages)

    def analyze(self, c: Call) -> None:
        self._analyze_impl(c, resources=True)

    def _analyze_impl(self, c: Call, resources: bool) -> None:
        def visit(arg, ctx) -> None:
            if isinstance(arg, PointerArg):
                if arg.is_null():
                    pass
                elif arg.vma_size != 0:
                    self.va.note_alloc(arg.address // self.target.page_size,
                                       arg.vma_size // self.target.page_size)
                else:
                    assert arg.res is not None
                    self.ma.note_alloc(arg.address, arg.res.size())
            t = arg.typ
            if isinstance(t, ResourceType):
                if resources and t.dir != Dir.IN:
                    assert t.desc is not None
                    self.resources.setdefault(t.desc.name, []).append(arg)
            elif isinstance(t, BufferType):
                if t.dir != Dir.OUT and isinstance(arg, DataArg) and len(arg.data) != 0:
                    val = bytes(arg.data)
                    # Strip trailing zero padding down to one terminator.
                    while len(val) >= 2 and val[-1] == 0 and val[-2] == 0:
                        val = val[:-1]
                    if t.kind == BufferKind.STRING:
                        try:
                            self.strings[val.decode("latin-1")] = True
                        except Exception:
                            pass
                    elif t.kind == BufferKind.FILENAME:
                        if len(val) < 3:
                            return  # special file, not one of ours
                        s = val.decode("latin-1")
                        if s.endswith("\x00"):
                            s = s[:-1]
                        self.files[s] = True

        foreach_arg(c, visit)


def analyze(ct, p: Prog, c: Optional[Call]) -> State:
    """Analyze p up to but not including c; resources created at or
    after c are not usable (reference: prog/analysis.go:26-36)."""
    s = State(p.target, ct)
    resources = True
    for c1 in p.calls:
        if c1 is c:
            resources = False
        s._analyze_impl(c1, resources)
    return s


def required_features(p: Prog) -> tuple[bool, bool]:
    """(bitmasks, csums) needed by the program
    (reference: prog/analysis.go:158-172)."""
    bitmasks = csums = False
    for c in p.calls:
        def visit(arg, ctx) -> None:
            nonlocal bitmasks, csums
            if isinstance(arg, ConstArg):
                if arg.typ.bitfield_offset() != 0 or arg.typ.bitfield_length() != 0:
                    bitmasks = True
            if isinstance(arg.typ, CsumType):
                csums = True

        foreach_arg(c, visit)
    return bitmasks, csums
