"""Random program generation.

Tree-recursive generation stays on the CPU by design: it is ~1/100 of
the fuzz loop (the TPU engine owns high-volume mutation of existing
corpus programs).  Semantics follow the reference generator
(reference: prog/generation.go:12-31, prog/rand.go:389-681).
"""

from __future__ import annotations

from typing import Optional

from syzkaller_tpu.models.analysis import State
from syzkaller_tpu.models.prog import (
    Arg,
    Call,
    ConstArg,
    DataArg,
    GroupArg,
    PointerArg,
    Prog,
    ResultArg,
    UnionArg,
    foreach_arg,
    make_return_arg,
)
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.size import assign_sizes_call
from syzkaller_tpu.models.types import (
    ArrayKind,
    ArrayType,
    BufferKind,
    BufferType,
    ConstType,
    CsumType,
    Dir,
    FlagsType,
    IntKind,
    IntType,
    LenType,
    ProcType,
    PtrType,
    ResourceType,
    StructType,
    Syscall,
    Type,
    UnionType,
    VmaType,
)


def generate_prog(target, rng: RandGen, ncalls: int, ct=None) -> Prog:
    """Generate a random program of length ~ncalls
    (reference: prog/generation.go:12-31)."""
    p = Prog(target=target)
    s = State(target, ct)
    while len(p.calls) < ncalls:
        calls = generate_call(rng, s, p)
        for c in calls:
            s.analyze(c)
            p.calls.append(c)
    return p


def generate_call(rng: RandGen, s: State, p: Prog) -> list[Call]:
    """Sample the next call from the choice table, biased by the calls
    already present (reference: prog/rand.go:389-402)."""
    target = rng.target
    if s.ct is None:
        idx = rng.intn(len(target.syscalls))
    else:
        call = -1
        if p.calls:
            call = p.calls[rng.intn(len(p.calls))].meta.id
        idx = s.ct.choose(rng, call)
    return generate_particular_call(rng, s, target.syscalls[idx])


def generate_particular_call(rng: RandGen, s: State, meta: Syscall) -> list[Call]:
    """(reference: prog/rand.go:404-416)"""
    c = Call(meta=meta, ret=make_return_arg(meta.ret))
    c.args, calls = generate_args(rng, s, meta.args)
    assign_sizes_call(c)
    calls.append(c)
    for c1 in calls:
        rng.target.sanitize_call(c1)
    return calls


def generate_args(rng: RandGen, s: State, types: list[Type]) -> tuple[list[Arg], list[Call]]:
    calls: list[Call] = []
    args: list[Arg] = []
    for typ in types:
        arg, calls1 = generate_arg(rng, s, typ)
        assert arg is not None, f"generated arg is nil for type {typ.name}"
        args.append(arg)
        calls.extend(calls1)
    return args, calls


def generate_arg(rng: RandGen, s: State, typ: Type) -> tuple[Arg, list[Call]]:
    return generate_arg_impl(rng, s, typ, ignore_special=False)


def generate_arg_impl(rng: RandGen, s: State, typ: Type,
                      ignore_special: bool) -> tuple[Arg, list[Call]]:
    """(reference: prog/rand.go:480-525)"""
    target = rng.target
    if typ.dir == Dir.OUT:
        # Output scalars need no interesting value, but must exist so
        # later calls can reference them.
        if isinstance(typ, (IntType, FlagsType, ConstType, ProcType,
                            VmaType, ResourceType)):
            return target.default_arg(typ), []

    if typ.optional and rng.one_of(5):
        return target.default_arg(typ), []

    # Bound recursion for optional pointers to structured types.
    if isinstance(typ, PtrType) and typ.optional and \
            isinstance(typ.elem, (StructType, ArrayType, UnionType)):
        name = typ.elem.name
        rng.rec_depth[name] = rng.rec_depth.get(name, 0) + 1
        try:
            if rng.rec_depth[name] >= 3:
                return PointerArg.make_null(typ), []
            return _generate_by_type(rng, s, typ, ignore_special)
        finally:
            rng.rec_depth[name] -= 1
            if rng.rec_depth[name] == 0:
                del rng.rec_depth[name]

    if not ignore_special and typ.dir != Dir.OUT:
        if isinstance(typ, (StructType, UnionType)):
            gen = target.special_types.get(typ.name)
            if gen is not None:
                from syzkaller_tpu.models.gen_api import Gen

                return gen(Gen(rng, s), typ, None)

    return _generate_by_type(rng, s, typ, ignore_special)


def _generate_by_type(rng: RandGen, s: State, typ: Type,
                      ignore_special: bool) -> tuple[Arg, list[Call]]:
    """Per-type generation (reference: prog/rand.go:527-681)."""
    target = rng.target

    if isinstance(typ, ResourceType):
        if rng.n_out_of(1000, 1011):
            # Reuse an existing resource.
            allres: list[ResultArg] = []
            for name1, res1 in sorted(s.resources.items()):
                assert typ.desc is not None
                if target.is_compatible_resource(typ.desc.name, name1) or \
                        (rng.one_of(20) and
                         target.is_compatible_resource(typ.desc.kind[0], name1)):
                    allres.extend(res1)
            if allres:
                return ResultArg(typ, allres[rng.intn(len(allres))], 0), []
            return create_resource(rng, s, typ)
        if rng.n_out_of(10, 11):
            return create_resource(rng, s, typ)
        special = typ.special_values()
        return ResultArg(typ, None, special[rng.intn(len(special))]), []

    if isinstance(typ, BufferType):
        return _generate_buffer(rng, s, typ), []

    if isinstance(typ, VmaType):
        npages = rng.rand_page_count()
        if typ.range_begin != 0 or typ.range_end != 0:
            npages = typ.range_begin + rng.intn(typ.range_end - typ.range_begin + 1)
        page = s.va.alloc(rng, npages)
        return PointerArg.make_vma(typ, page * target.page_size,
                                   npages * target.page_size), []

    if isinstance(typ, FlagsType):
        return ConstArg(typ, rng.flags(typ.vals)), []

    if isinstance(typ, ConstType):
        return ConstArg(typ, typ.val), []

    if isinstance(typ, IntType):
        v = rng.rand_int()
        if typ.kind == IntKind.FILEOFF:
            if rng.n_out_of(90, 101):
                v = 0
            elif rng.n_out_of(10, 11):
                v = rng.rand(100)
            else:
                v = rng.rand_int()
        elif typ.kind == IntKind.RANGE:
            v = rng.rand_range_int(typ.range_begin, typ.range_end)
        return ConstArg(typ, v), []

    if isinstance(typ, ProcType):
        return ConstArg(typ, rng.rand(typ.values_per_proc)), []

    if isinstance(typ, ArrayType):
        assert typ.elem is not None
        if typ.kind == ArrayKind.RAND_LEN:
            count = rng.rand_array_len()
        else:
            count = rng.rand_range(typ.range_begin, typ.range_end)
        inner: list[Arg] = []
        calls: list[Call] = []
        for _ in range(count):
            arg1, calls1 = generate_arg(rng, s, typ.elem)
            inner.append(arg1)
            calls.extend(calls1)
        return GroupArg(typ, inner), calls

    if isinstance(typ, StructType):
        args, calls = generate_args(rng, s, typ.fields)
        return GroupArg(typ, args), calls

    if isinstance(typ, UnionType):
        opt_type = typ.fields[rng.intn(len(typ.fields))]
        opt, calls = generate_arg(rng, s, opt_type)
        return UnionArg(typ, opt), calls

    if isinstance(typ, PtrType):
        assert typ.elem is not None
        inner, calls = generate_arg(rng, s, typ.elem)
        return alloc_addr(rng, s, typ, inner.size(), inner), calls

    if isinstance(typ, LenType):
        return ConstArg(typ, 0), []  # filled by assign_sizes_call

    if isinstance(typ, CsumType):
        return ConstArg(typ, 0), []  # computed by the executor

    raise TypeError(f"unknown type {typ}")


def _generate_buffer(rng: RandGen, s: State, typ: BufferType) -> Arg:
    """(reference: prog/rand.go:553-598)"""
    if typ.kind in (BufferKind.BLOB_RAND, BufferKind.BLOB_RANGE):
        sz = rng.rand_buf_len()
        if typ.kind == BufferKind.BLOB_RANGE:
            sz = rng.rand_range(typ.range_begin, typ.range_end)
        if typ.dir == Dir.OUT:
            return DataArg(typ, out_size=sz)
        return DataArg(typ, bytes(rng.intn(256) for _ in range(sz)))
    if typ.kind == BufferKind.STRING:
        data = rng.rand_string(s, typ)
        if typ.dir == Dir.OUT:
            return DataArg(typ, out_size=len(data))
        return DataArg(typ, data)
    if typ.kind == BufferKind.FILENAME:
        if typ.dir == Dir.OUT:
            if not typ.varlen:
                sz = typ.size()
            elif rng.n_out_of(1, 3):
                sz = rng.rand(100)
            elif rng.n_out_of(1, 2):
                sz = 108  # UNIX_PATH_MAX
            else:
                sz = 4096  # PATH_MAX
            return DataArg(typ, out_size=sz)
        return DataArg(typ, rng.filename(s, typ).encode("latin-1"))
    if typ.kind == BufferKind.TEXT:
        if typ.dir == Dir.OUT:
            return DataArg(typ, out_size=rng.intn(100))
        return DataArg(typ, rng.generate_text(typ.text))
    raise TypeError(f"unknown buffer kind {typ.kind}")


def alloc_addr(rng: RandGen, s: State, typ: Type, size: int, data: Arg) -> PointerArg:
    return PointerArg(typ, s.ma.alloc(rng, size), data)


def alloc_vma(rng: RandGen, s: State, typ: Type, num_pages: int) -> PointerArg:
    page = s.va.alloc(rng, num_pages)
    return PointerArg.make_vma(typ, page * rng.target.page_size,
                               num_pages * rng.target.page_size)


def create_resource(rng: RandGen, s: State, res: ResourceType) -> tuple[Arg, list[Call]]:
    """Recursively generate a constructor call producing the resource
    (reference: prog/rand.go:248-321)."""
    target = rng.target
    assert res.desc is not None
    if rng.in_create_resource:
        special = res.special_values()
        return ResultArg(res, None, special[rng.intn(len(special))]), []
    rng.in_create_resource = True
    try:
        kind = res.desc.name
        if rng.one_of(1000):
            # Spoof resource subkind.
            alls = [k for k in sorted(target.resource_map)
                    if target.is_compatible_resource(res.desc.kind[0], k)]
            if alls:
                kind = alls[rng.intn(len(alls))]
        metas = [m for m in target.resource_ctors.get(kind, [])
                 if s.ct is None or s.ct.enabled_by_id(m.id)]
        if not metas:
            return ResultArg(res, None, res.default()), []
        for _ in range(1000):
            meta = metas[rng.intn(len(metas))]
            calls = generate_particular_call(rng, s, meta)
            s1 = State(target, s.ct)
            s1.analyze(calls[-1])
            allres: list[ResultArg] = []
            for kind1, res1 in sorted(s1.resources.items()):
                if target.is_compatible_resource(kind, kind1):
                    allres.extend(res1)
            if allres:
                return ResultArg(res, allres[rng.intn(len(allres))], 0), calls
            # Unsuccessful: unlink and retry.
            for c in calls:
                def unlink(arg, ctx):
                    if isinstance(arg, ResultArg) and arg.res is not None:
                        arg.res.uses.discard(arg)
                foreach_arg(c, unlink)
        raise RuntimeError(
            f"failed to create a resource {res.desc.kind[0]} with "
            f"{[m.name for m in metas]}")
    finally:
        rng.in_create_resource = False
