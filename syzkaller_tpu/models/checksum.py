"""Inet/pseudo-header checksum dependency graph.

Checksums are computed at runtime by the executor; here we only build
the instruction graph describing what to checksum
(reference: prog/checksum.go:10-167).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

from syzkaller_tpu.models.prog import Arg, Call, GroupArg, foreach_arg, inner_arg
from syzkaller_tpu.models.types import CsumKind, CsumType, StructType
from syzkaller_tpu.utils.ints import swap_int


class CsumChunkKind(enum.IntEnum):
    ARG = 0
    CONST = 1


@dataclass
class CsumChunk:
    kind: CsumChunkKind
    arg: Optional[Arg] = None  # for ARG
    value: int = 0  # for CONST
    size: int = 0  # for CONST


@dataclass
class CsumInfo:
    kind: CsumKind
    chunks: list[CsumChunk] = field(default_factory=list)


def calc_checksums_call(c: Call) -> Optional[dict[int, tuple[Arg, CsumInfo]]]:
    """Returns {id(csum_arg): (csum_arg, info)} or None
    (reference: prog/checksum.go:29-113)."""
    inet_fields: list[Arg] = []
    pseudo_fields: list[Arg] = []

    def find(arg, ctx) -> None:
        t = arg.typ
        if isinstance(t, CsumType):
            if t.kind == CsumKind.INET:
                inet_fields.append(arg)
            elif t.kind == CsumKind.PSEUDO:
                pseudo_fields.append(arg)
            else:
                raise ValueError(f"unknown csum kind {t.kind}")

    foreach_arg(c, find)
    if not inet_fields and not pseudo_fields:
        return None

    parents: dict[int, Arg] = {}

    def note_parents(arg, ctx) -> None:
        if isinstance(arg.typ, StructType):
            assert isinstance(arg, GroupArg)
            for f in arg.inner:
                fi = inner_arg(f)
                if fi is not None:
                    parents[id(fi)] = arg

    foreach_arg(c, note_parents)

    csum_map: dict[int, tuple[Arg, CsumInfo]] = {}
    for arg in inet_fields:
        t = arg.typ
        assert isinstance(t, CsumType)
        csummed = _find_csummed_arg(arg, t, parents)
        info = CsumInfo(kind=CsumKind.INET,
                        chunks=[CsumChunk(CsumChunkKind.ARG, csummed)])
        csum_map[id(arg)] = (arg, info)

    if not pseudo_fields:
        return csum_map

    # Locate the enclosing ipv4/ipv6 header to source the pseudo-header
    # address fields (reference: prog/checksum.go:79-96).  Recognized by
    # the conventional src_ip/dst_ip field names and sizes.
    ip_src = ip_dst = None

    def find_hdr(arg, ctx) -> None:
        nonlocal ip_src, ip_dst
        if not isinstance(arg, GroupArg):
            return
        fields = {f.typ.field_name: f for f in arg.inner}
        src, dst = fields.get("src_ip"), fields.get("dst_ip")
        if src is None or dst is None:
            return
        if src.size() == dst.size() and src.size() in (4, 16):
            ip_src, ip_dst = src, dst

    foreach_arg(c, find_hdr)
    assert ip_src is not None and ip_dst is not None, \
        "no ipv4 nor ipv6 header found"

    for arg in pseudo_fields:
        t = arg.typ
        assert isinstance(t, CsumType)
        csummed = _find_csummed_arg(arg, t, parents)
        proto = t.protocol & 0xFF
        if ip_src.size() == 4:
            info = _pseudo_ipv4(csummed, ip_src, ip_dst, proto)
        else:
            info = _pseudo_ipv6(csummed, ip_src, ip_dst, proto)
        csum_map[id(arg)] = (arg, info)
    return csum_map


def _find_csummed_arg(arg: Arg, typ: CsumType, parents: dict[int, Arg]) -> Arg:
    """(reference: prog/checksum.go:115-129)"""
    if typ.buf == "parent":
        p = parents.get(id(arg))
        assert p is not None, f"parent for {typ.name} not in parents map"
        return p
    p = parents.get(id(arg))
    while p is not None:
        if typ.buf == p.typ.name:
            return p
        p = parents.get(id(p))
    raise ValueError(
        f"csum field {typ.field_name!r} references nonexistent field {typ.buf!r}")


def _pseudo_ipv4(pkt: Arg, src: Arg, dst: Arg, proto: int) -> CsumInfo:
    return CsumInfo(kind=CsumKind.INET, chunks=[
        CsumChunk(CsumChunkKind.ARG, src),
        CsumChunk(CsumChunkKind.ARG, dst),
        CsumChunk(CsumChunkKind.CONST, None, swap_int(proto, 2), 2),
        CsumChunk(CsumChunkKind.CONST, None, swap_int(pkt.size() & 0xFFFF, 2), 2),
        CsumChunk(CsumChunkKind.ARG, pkt),
    ])


def _pseudo_ipv6(pkt: Arg, src: Arg, dst: Arg, proto: int) -> CsumInfo:
    return CsumInfo(kind=CsumKind.INET, chunks=[
        CsumChunk(CsumChunkKind.ARG, src),
        CsumChunk(CsumChunkKind.ARG, dst),
        CsumChunk(CsumChunkKind.CONST, None, swap_int(pkt.size() & 0xFFFFFFFF, 4), 4),
        CsumChunk(CsumChunkKind.CONST, None, swap_int(proto, 4), 4),
        CsumChunk(CsumChunkKind.ARG, pkt),
    ])
