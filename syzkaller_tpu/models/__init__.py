"""Program model: the typed syscall-program representation.

This package is the equivalent of the reference's prog/ package
(reference: prog/prog.go, prog/types.go, prog/target.go): a pure
in-memory model of syscall programs with no I/O, the substrate both
for the CPU semantics engine and for the flat program-tensor codec
consumed by the TPU kernels in syzkaller_tpu.ops.
"""

from syzkaller_tpu.models.types import (  # noqa: F401
    Dir,
    Type,
    ResourceType,
    ConstType,
    IntType,
    IntKind,
    FlagsType,
    LenType,
    ProcType,
    CsumType,
    CsumKind,
    VmaType,
    BufferType,
    BufferKind,
    TextKind,
    ArrayType,
    ArrayKind,
    PtrType,
    StructType,
    UnionType,
    Syscall,
    ResourceDesc,
    ConstValue,
    foreach_type,
    is_pad,
)
from syzkaller_tpu.models.prog import (  # noqa: F401
    Arg,
    ConstArg,
    PointerArg,
    DataArg,
    GroupArg,
    UnionArg,
    ResultArg,
    Call,
    Prog,
    foreach_arg,
    foreach_sub_arg,
    ArgCtx,
)
from syzkaller_tpu.models.target import Target, register_target, get_target  # noqa: F401
