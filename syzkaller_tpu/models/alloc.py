"""Virtual-memory allocators giving deterministic fake addresses to
pointer args (reference: prog/alloc.go:17-164).

mem_alloc: 64-byte-granule bitmap allocator with "bankruptcy" reset when
the address space fills up.  vma_alloc: page allocator biased towards
reusing/abutting previously used pages.
"""

from __future__ import annotations

MEM_ALLOC_GRANULE = 64
MEM_ALLOC_MAX_MEM = 16 << 20


class MemAlloc:
    def __init__(self, total_mem_size: int):
        assert total_mem_size <= MEM_ALLOC_MAX_MEM
        self.size = total_mem_size // MEM_ALLOC_GRANULE
        # One Python int as a bitmap of granules; dense but simple.
        self.bits = 0

    def note_alloc(self, addr0: int, size0: int) -> None:
        addr = addr0 // MEM_ALLOC_GRANULE
        end = (addr0 + size0 + MEM_ALLOC_GRANULE - 1) // MEM_ALLOC_GRANULE
        n = end - addr
        self.bits |= ((1 << n) - 1) << addr

    def alloc(self, rng, size0: int) -> int:
        if size0 == 0:
            size0 = 1
        size = (size0 + MEM_ALLOC_GRANULE - 1) // MEM_ALLOC_GRANULE
        mask = (1 << size) - 1
        end = self.size - size
        start = 0
        bits = self.bits
        while start < end:
            if (bits >> start) & mask == 0:
                start0 = start * MEM_ALLOC_GRANULE
                self.note_alloc(start0, size0)
                return start0
            start += 1
        # Address space exhausted: reset and start over
        # (reference: prog/alloc.go:74-87).
        self.bits = 0
        return self.alloc(rng, size0)


class VmaAlloc:
    def __init__(self, total_pages: int):
        self.num_pages = total_pages
        self.used: list[int] = []
        self._used_set: set[int] = set()

    def note_alloc(self, page: int, size: int) -> None:
        for i in range(page, page + size):
            if i not in self._used_set:
                self._used_set.add(i)
                self.used.append(i)

    def alloc(self, rng, size: int) -> int:
        """rng is a models.rand.RandGen (reference: prog/alloc.go:136-164)."""
        assert size <= self.num_pages
        if not self.used or rng.one_of(5):
            page = rng.rand(4)
            if not rng.one_of(100):
                page = self.num_pages - page - size
        else:
            page = self.used[rng.rand(len(self.used))]
            if size > 1 and rng.bin():
                off = rng.rand(size)
                if off > page:
                    off = page
                page -= off
            if page + size > self.num_pages:
                page = self.num_pages - size
        assert 0 <= page < self.num_pages and page + size <= self.num_pages
        self.note_alloc(page, size)
        return page
