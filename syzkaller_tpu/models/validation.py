"""Structural program validation, enabled in tests
(reference: prog/validation.go:12-249)."""

from __future__ import annotations

from syzkaller_tpu.models.prog import (
    Arg,
    ConstArg,
    DataArg,
    GroupArg,
    PointerArg,
    Prog,
    ResultArg,
    UnionArg,
)
from syzkaller_tpu.models.types import (
    ArrayKind,
    ArrayType,
    BufferKind,
    BufferType,
    ConstType,
    CsumType,
    Dir,
    FlagsType,
    IntType,
    LenType,
    ProcType,
    PtrType,
    ResourceType,
    StructType,
    UnionType,
    VmaType,
)

# Toggled by tests to validate after every random op.
debug = False


class ValidationError(Exception):
    pass


def validate_prog(p: Prog) -> None:
    args_seen: set[int] = set()
    uses: dict[ResultArg, ResultArg] = {}

    def validate_arg(arg: Arg) -> None:
        if arg is None:
            raise ValidationError("nil arg")
        if id(arg) in args_seen:
            raise ValidationError(f"arg referenced several times in the tree: {arg}")
        if arg.typ is None:
            raise ValidationError("no arg type")
        args_seen.add(id(arg))
        t = arg.typ
        if isinstance(arg, ConstArg):
            if isinstance(t, IntType):
                if t.dir == Dir.OUT and arg.val not in (0, t.default()):
                    raise ValidationError(f"out int arg {t.name} has value {arg.val}")
            elif isinstance(t, ProcType):
                if arg.val >= t.values_per_proc and arg.val != t.default():
                    raise ValidationError(f"per-proc arg {t.name} has bad value {arg.val}")
            elif isinstance(t, CsumType):
                if arg.val != 0:
                    raise ValidationError(f"csum arg {t.name} has nonzero value")
            elif not isinstance(t, (ConstType, FlagsType, LenType)):
                raise ValidationError(f"const arg has bad type {t.name}")
            if t.dir == Dir.OUT and not isinstance(t, LenType):
                if arg.val not in (0, t.default()):
                    raise ValidationError(
                        f"output arg {t.field_name}/{t.name} has non-default value")
        elif isinstance(arg, ResultArg):
            if not isinstance(t, ResourceType):
                raise ValidationError(f"result arg has bad type {t.name}")
            for u in arg.uses:
                uses[u] = arg
            if t.dir == Dir.OUT and arg.val not in (0, t.default()):
                raise ValidationError(f"out resource arg {t.name} has value {arg.val}")
            if arg.res is not None:
                if id(arg.res) not in args_seen:
                    raise ValidationError(
                        f"result arg {t.name} references out-of-tree result")
                if arg not in arg.res.uses:
                    raise ValidationError(f"result arg {t.name} has broken uses link")
        elif isinstance(arg, DataArg):
            if not isinstance(t, BufferType):
                raise ValidationError(f"data arg has bad type {t.name}")
            if t.dir == Dir.OUT and len(arg.data) != 0:
                raise ValidationError(f"output arg {t.name} has data")
            if not t.varlen and t.size() != arg.size():
                raise ValidationError(
                    f"data arg {t.name} has size {arg.size()}, want {t.size()}")
            if t.kind == BufferKind.STRING and t.type_size != 0 and \
                    arg.size() != t.type_size:
                raise ValidationError(
                    f"string arg {t.name} has size {arg.size()}, want {t.type_size}")
        elif isinstance(arg, GroupArg):
            if isinstance(t, StructType):
                if len(arg.inner) != len(t.fields):
                    raise ValidationError(
                        f"struct arg {t.name} has {len(arg.inner)} fields, "
                        f"want {len(t.fields)}")
            elif isinstance(t, ArrayType):
                if t.kind == ArrayKind.RANGE_LEN and t.range_begin == t.range_end \
                        and len(arg.inner) != t.range_begin:
                    raise ValidationError(
                        f"array {t.name} has {len(arg.inner)} elems, "
                        f"want {t.range_begin}")
            else:
                raise ValidationError(f"group arg has bad type {t.name}")
            for sub in arg.inner:
                validate_arg(sub)
        elif isinstance(arg, UnionArg):
            if not isinstance(t, UnionType):
                raise ValidationError(f"union arg has bad type {t.name}")
            if not any(arg.option.typ.name == f.name for f in t.fields):
                raise ValidationError(f"union arg {t.name} has bad option")
            validate_arg(arg.option)
        elif isinstance(arg, PointerArg):
            max_mem = p.target.num_pages * p.target.page_size
            size = arg.vma_size
            if size == 0 and arg.res is not None:
                size = arg.res.size()
            if arg.address >= max_mem or arg.address + size > max_mem:
                raise ValidationError(
                    f"ptr {t.name} has bad address {arg.address:#x}/{size:#x}")
            if isinstance(t, VmaType):
                if arg.res is not None:
                    raise ValidationError(f"vma arg {t.name} has data")
                if arg.vma_size == 0 and t.dir != Dir.OUT and not t.optional:
                    raise ValidationError(f"vma arg {t.name} has size 0")
            elif isinstance(t, PtrType):
                if arg.res is None and not t.optional:
                    raise ValidationError(f"non-optional pointer {t.name} is nil")
                if arg.res is not None:
                    validate_arg(arg.res)
                if arg.vma_size != 0:
                    raise ValidationError(f"pointer arg {t.name} has nonzero vma size")
                if t.dir == Dir.OUT:
                    raise ValidationError(f"pointer arg {t.name} is output")
            else:
                raise ValidationError(f"ptr arg has bad type {t.name}")
        else:
            raise ValidationError(f"unknown arg kind {arg!r}")

    for c in p.calls:
        if c.meta is None:
            raise ValidationError("call without meta")
        if len(c.args) != len(c.meta.args):
            raise ValidationError(
                f"{c.meta.name}: want {len(c.meta.args)} args, got {len(c.args)}")
        for arg in c.args:
            validate_arg(arg)
        # return value
        if c.meta.ret is None:
            if c.ret is not None:
                raise ValidationError(f"{c.meta.name}: return value without type")
        else:
            if c.ret is None:
                raise ValidationError(f"{c.meta.name}: return value is absent")
            if c.ret.typ is not c.meta.ret:
                raise ValidationError(f"{c.meta.name}: wrong return type")
            if c.ret.typ.dir != Dir.OUT:
                raise ValidationError(f"{c.meta.name}: return value is not output")
            if c.ret.res is not None or c.ret.val != 0 or c.ret.op_div != 0 \
                    or c.ret.op_add != 0:
                raise ValidationError(f"{c.meta.name}: return value is not empty")
            validate_arg(c.ret)

    for u in uses:
        if id(u) not in args_seen:
            raise ValidationError("use refers to an out-of-tree arg")
