"""Text serialization of programs — wire-compatible with the reference
format (`r0 = open(&(0x7f0000000000)='./file0\\x00', 0x1)`), so corpora
and crash logs from the reference can be imported directly
(reference: prog/encoding.go:26-869).

The parser is deliberately tolerant: unknown args and excess fields are
eaten (eat_excessive) so cross-version corpora survive description
changes.
"""

from __future__ import annotations

import binascii
from typing import Optional

from syzkaller_tpu.models.prog import (
    Arg,
    Call,
    ConstArg,
    DataArg,
    GroupArg,
    PointerArg,
    Prog,
    ResultArg,
    UnionArg,
    default_arg,
    is_default_arg,
    make_return_arg,
)
from syzkaller_tpu.models.types import (
    ArrayKind,
    ArrayType,
    BufferType,
    ConstType,
    CsumType,
    Dir,
    FlagsType,
    IntType,
    LenType,
    ProcType,
    PtrType,
    ResourceType,
    StructType,
    Type,
    UnionType,
    VmaType,
    is_pad,
)

ENCODING_ADDR_BASE = 0x7F0000000000
MAX_LINE_LEN = 1 << 20


def prog_string(p: Prog) -> str:
    """Compact debug form: call names joined by '-'."""
    return "-".join(c.meta.name for c in p.calls)


def serialize_prog(p: Prog) -> bytes:
    from syzkaller_tpu.models import validation

    if validation.debug:
        validation.validate_prog(p)
    out: list[str] = []
    vars_: dict[ResultArg, int] = {}
    var_seq = [0]
    for c in p.calls:
        line: list[str] = []
        if c.ret is not None and len(c.ret.uses) != 0:
            line.append(f"r{var_seq[0]} = ")
            vars_[c.ret] = var_seq[0]
            var_seq[0] += 1
        line.append(f"{c.meta.name}(")
        first = True
        for a in c.args:
            if is_pad(a.typ):
                continue
            if not first:
                line.append(", ")
            first = False
            line.append(_serialize_arg(p.target, a, vars_, var_seq))
        line.append(")")
        out.append("".join(line))
    return ("\n".join(out) + "\n").encode("latin-1") if out else b""


def _serialize_arg(target, arg: Optional[Arg], vars_: dict, var_seq: list[int]) -> str:
    from syzkaller_tpu.models.any_squash import is_any_ptr

    if arg is None:
        return "nil"
    if isinstance(arg, ConstArg):
        return f"0x{arg.val:x}"
    if isinstance(arg, PointerArg):
        if arg.is_null():
            return "0x0"
        s = f"&{_serialize_addr(arg)}"
        if arg.res is None or not is_default_arg(target, arg.res) \
                or is_any_ptr(target, arg.typ):
            s += "="
            if is_any_ptr(target, arg.typ):
                s += "ANY="
            s += _serialize_arg(target, arg.res, vars_, var_seq)
        return s
    if isinstance(arg, DataArg):
        if arg.typ.dir == Dir.OUT:
            return f'""/{arg.size()}'
        data = bytes(arg.data)
        if not arg.typ.varlen:
            # Statically-typed data is zero-padded on parse; strip here.
            while len(data) >= 2 and data[-1] == 0 and data[-2] == 0:
                data = data[:-1]
        return _serialize_data(data)
    if isinstance(arg, GroupArg):
        if isinstance(arg.typ, StructType):
            od, cd = "{", "}"
        elif isinstance(arg.typ, ArrayType):
            od, cd = "[", "]"
        else:
            raise TypeError("unknown group type")
        last = len(arg.inner) - 1
        if arg.fixed_inner_size():
            while last >= 0 and is_default_arg(target, arg.inner[last]):
                last -= 1
        parts: list[str] = []
        for i in range(last + 1):
            a1 = arg.inner[i]
            if a1 is not None and is_pad(a1.typ):
                continue
            if i != 0:
                parts.append(", ")
            parts.append(_serialize_arg(target, a1, vars_, var_seq))
        return od + "".join(parts) + cd
    if isinstance(arg, UnionArg):
        s = f"@{arg.option.typ.field_name}"
        if not is_default_arg(target, arg.option):
            s += "=" + _serialize_arg(target, arg.option, vars_, var_seq)
        return s
    if isinstance(arg, ResultArg):
        s = ""
        if len(arg.uses) != 0:
            s += f"<r{var_seq[0]}=>"
            vars_[arg] = var_seq[0]
            var_seq[0] += 1
        if arg.res is None:
            return s + f"0x{arg.val:x}"
        rid = vars_.get(arg.res)
        assert rid is not None, "no result"
        s += f"r{rid}"
        if arg.op_div != 0:
            s += f"/{arg.op_div}"
        if arg.op_add != 0:
            s += f"+{arg.op_add}"
        return s
    raise TypeError(f"unknown arg kind {arg!r}")


def _serialize_addr(arg: PointerArg) -> str:
    ssize = f"/0x{arg.vma_size:x}" if arg.vma_size != 0 else ""
    return f"(0x{ENCODING_ADDR_BASE + arg.address:x}{ssize})"


def _serialize_data(data: bytes) -> str:
    special = {0: "\\x00", 7: "\\a", 8: "\\b", 12: "\\f", 10: "\\n",
               13: "\\r", 9: "\\t", 11: "\\v", 0x27: "\\'", 0x5C: "\\\\"}
    readable = all(0x20 <= v < 0x7F or v in special for v in data)
    if not readable or len(data) == 0:
        return f'"{binascii.hexlify(data).decode()}"'
    out = ["'"]
    for v in data:
        if v in special:
            out.append(special[v])
        else:
            out.append(chr(v))
    out.append("'")
    return "".join(out)


# -- deserialization -----------------------------------------------------


class ParseError(Exception):
    pass


class _Parser:
    """Single-line cursor with identifier/char helpers
    (reference: prog/encoding.go:726-832)."""

    def __init__(self, line: str, lineno: int):
        self.s = line
        self.i = 0
        self.l = lineno

    def eof(self) -> bool:
        return self.i == len(self.s)

    def char(self) -> str:
        if self.eof():
            raise ParseError(f"unexpected eof (line #{self.l}: {self.s})")
        return self.s[self.i]

    def parse(self, ch: str) -> None:
        if self.eof():
            raise ParseError(f"want {ch!r}, got EOF (line #{self.l})")
        if self.s[self.i] != ch:
            raise ParseError(
                f"want {ch!r}, got {self.s[self.i]!r} (line #{self.l}: {self.s})")
        self.i += 1
        self.skip_ws()

    def consume(self) -> str:
        if self.eof():
            raise ParseError(f"unexpected eof (line #{self.l})")
        v = self.s[self.i]
        self.i += 1
        return v

    def skip_ws(self) -> None:
        while self.i < len(self.s) and self.s[self.i] in " \t":
            self.i += 1

    def ident(self) -> str:
        i = self.i
        while self.i < len(self.s) and (
                self.s[self.i].isalnum() or self.s[self.i] in "_$"):
            self.i += 1
        if i == self.i:
            raise ParseError(
                f"failed to parse identifier at pos {i} (line #{self.l}: {self.s})")
        s = self.s[i:self.i]
        self.skip_ws()
        return s


def deserialize_prog(target, data: bytes) -> Prog:
    """(reference: prog/encoding.go:153-226)"""
    prog = Prog(target=target)
    vars_: dict[str, ResultArg] = {}
    for lineno, raw in enumerate(data.decode("latin-1").splitlines(), 1):
        if not raw or raw.startswith("#"):
            continue
        p = _Parser(raw, lineno)
        p.skip_ws()
        if p.eof():
            continue
        name = p.ident()
        r = ""
        if not p.eof() and p.char() == "=":
            r = name
            p.parse("=")
            name = p.ident()
        meta = target.syscall_map.get(name)
        if meta is None:
            raise ParseError(f"unknown syscall {name} (line #{lineno})")
        c = Call(meta=meta, ret=make_return_arg(meta.ret))
        prog.calls.append(c)
        p.parse("(")
        i = 0
        while p.char() != ")":
            if i >= len(meta.args):
                _eat_excessive(p, stop_at_comma=False)
                break
            typ = meta.args[i]
            if is_pad(typ):
                raise ParseError(f"padding in syscall {name} arguments")
            arg = _parse_arg(target, typ, p, vars_)
            c.args.append(arg)
            if p.char() != ")":
                p.parse(",")
            i += 1
        p.parse(")")
        if not p.eof():
            raise ParseError(f"trailing data (line #{lineno})")
        for j in range(len(c.args), len(meta.args)):
            c.args.append(default_arg(target, meta.args[j]))
        if len(c.args) != len(meta.args):
            raise ParseError(
                f"wrong call arg count: {len(c.args)}, want {len(meta.args)}")
        if r and c.ret is not None:
            vars_[r] = c.ret
    # Always validate: deserialization doesn't catch everything and we
    # receive programs from corpus/hub (reference: prog/encoding.go:216-221).
    from syzkaller_tpu.models.validation import validate_prog

    validate_prog(prog)
    for c in prog.calls:
        target.sanitize_call(c)
    return prog


def _parse_arg(target, typ: Optional[Type], p: _Parser, vars_: dict) -> Optional[Arg]:
    r = ""
    if p.char() == "<":
        p.parse("<")
        r = p.ident()
        p.parse("=")
        p.parse(">")
    arg = _parse_arg_impl(target, typ, p, vars_)
    if arg is None:
        if typ is not None:
            arg = default_arg(target, typ)
        elif r:
            raise ParseError("named nil argument")
    if r and isinstance(arg, ResultArg):
        vars_[r] = arg
    return arg


def _parse_arg_impl(target, typ, p: _Parser, vars_):
    ch = p.char()
    if ch == "0":
        return _parse_arg_int(target, typ, p)
    if ch == "r":
        return _parse_arg_res(target, typ, p, vars_)
    if ch == "&":
        return _parse_arg_addr(target, typ, p, vars_)
    if ch in "\"'":
        return _parse_arg_string(target, typ, p)
    if ch == "{":
        return _parse_arg_struct(target, typ, p, vars_)
    if ch == "[":
        return _parse_arg_array(target, typ, p, vars_)
    if ch == "@":
        return _parse_arg_union(target, typ, p, vars_)
    if ch == "n":
        p.parse("n")
        p.parse("i")
        p.parse("l")
        return None
    raise ParseError(f"failed to parse argument at {ch!r} "
                     f"(line #{p.l}/{p.i}: {p.s})")


def _parse_arg_int(target, typ, p: _Parser):
    val = p.ident()
    try:
        v = int(val, 0)
    except ValueError as e:
        raise ParseError(f"wrong arg value {val!r}: {e}")
    if isinstance(typ, (ConstType, IntType, FlagsType, ProcType, LenType, CsumType)):
        return ConstArg(typ, v)
    if isinstance(typ, ResourceType):
        return ResultArg(typ, None, v)
    if isinstance(typ, (PtrType, VmaType)):
        if typ.optional:
            return PointerArg.make_null(typ)
        return default_arg(target, typ)
    _eat_excessive(p, stop_at_comma=True)
    return default_arg(target, typ)


def _parse_arg_res(target, typ, p: _Parser, vars_):
    id_ = p.ident()
    div = add = 0
    if not p.eof() and p.char() == "/":
        p.parse("/")
        div = int(p.ident(), 0)
    if not p.eof() and p.char() == "+":
        p.parse("+")
        add = int(p.ident(), 0)
    v = vars_.get(id_)
    if v is None:
        return default_arg(target, typ)
    arg = ResultArg(typ, v, 0)
    arg.op_div = div
    arg.op_add = add
    return arg


def _parse_arg_addr(target, typ, p: _Parser, vars_):
    from syzkaller_tpu.models.any_squash import get_any, make_any_ptr_type

    if isinstance(typ, PtrType):
        typ1 = typ.elem
    elif isinstance(typ, VmaType):
        typ1 = None
    else:
        _eat_excessive(p, stop_at_comma=True)
        return default_arg(target, typ)
    p.parse("&")
    addr, vma_size = _parse_addr(target, p)
    inner = None
    if not p.eof() and p.char() == "=":
        p.parse("=")
        if p.char() == "A":
            p.parse("A")
            p.parse("N")
            p.parse("Y")
            p.parse("=")
            typ = make_any_ptr_type(target, typ.size(), typ.field_name)
            typ1 = get_any(target).array
        inner = _parse_arg(target, typ1, p, vars_)
    if typ1 is None:
        return PointerArg.make_vma(typ, addr, vma_size)
    if inner is None:
        inner = default_arg(target, typ1)
    return PointerArg(typ, addr, inner)


def _parse_addr(target, p: _Parser) -> tuple[int, int]:
    p.parse("(")
    addr = int(p.ident(), 0)
    if addr < ENCODING_ADDR_BASE:
        raise ParseError(f"address without base offset: {addr:#x}")
    addr -= ENCODING_ADDR_BASE
    if not p.eof() and p.char() in "+-":
        minus = p.char() == "-"
        p.parse(p.char())
        off = int(p.ident(), 0)
        addr = addr - off if minus else addr + off
    max_mem = target.num_pages * target.page_size
    vma_size = 0
    if not p.eof() and p.char() == "/":
        p.parse("/")
        size = int(p.ident(), 0)
        addr &= ~(target.page_size - 1)
        vma_size = (size + target.page_size - 1) & ~(target.page_size - 1)
        if vma_size == 0:
            vma_size = target.page_size
        if vma_size > max_mem:
            vma_size = max_mem
        if addr > max_mem - vma_size:
            addr = max_mem - vma_size
    p.parse(")")
    return addr, vma_size


def _parse_arg_string(target, typ, p: _Parser):
    if not isinstance(typ, BufferType):
        _eat_excessive(p, stop_at_comma=True)
        return default_arg(target, typ)
    data = _deserialize_data(p)
    size = None
    if not p.eof() and p.char() == "/":
        p.parse("/")
        size = int(p.ident(), 0)
    if not typ.varlen:
        size = typ.size()
    elif size is None:
        size = len(data)
    if typ.dir == Dir.OUT:
        return DataArg(typ, out_size=size)
    if size > len(data):
        data = data + bytes(size - len(data))
    return DataArg(typ, data[:size])


def _parse_arg_struct(target, typ, p: _Parser, vars_):
    p.parse("{")
    if not isinstance(typ, StructType):
        _eat_excessive(p, stop_at_comma=False)
        p.parse("}")
        return default_arg(target, typ)
    inner: list[Arg] = []
    i = 0
    while p.char() != "}":
        if i >= len(typ.fields):
            _eat_excessive(p, stop_at_comma=False)
            break
        fld = typ.fields[i]
        if is_pad(fld):
            inner.append(ConstArg(fld, 0))
        else:
            arg = _parse_arg(target, fld, p, vars_)
            inner.append(arg)
            if p.char() != "}":
                p.parse(",")
        i += 1
    p.parse("}")
    while len(inner) < len(typ.fields):
        inner.append(default_arg(target, typ.fields[len(inner)]))
    return GroupArg(typ, inner)


def _parse_arg_array(target, typ, p: _Parser, vars_):
    p.parse("[")
    if not isinstance(typ, ArrayType):
        _eat_excessive(p, stop_at_comma=False)
        p.parse("]")
        return default_arg(target, typ)
    inner: list[Arg] = []
    while p.char() != "]":
        inner.append(_parse_arg(target, typ.elem, p, vars_))
        if p.char() != "]":
            p.parse(",")
    p.parse("]")
    if typ.kind == ArrayKind.RANGE_LEN and typ.range_begin == typ.range_end:
        while len(inner) < typ.range_begin:
            inner.append(default_arg(target, typ.elem))
        del inner[typ.range_begin:]
    return GroupArg(typ, inner)


def _parse_arg_union(target, typ, p: _Parser, vars_):
    if not isinstance(typ, UnionType):
        _eat_excessive(p, stop_at_comma=True)
        return default_arg(target, typ)
    p.parse("@")
    name = p.ident()
    opt_type = next((t2 for t2 in typ.fields if t2.field_name == name), None)
    if opt_type is None:
        _eat_excessive(p, stop_at_comma=True)
        return default_arg(target, typ)
    if not p.eof() and p.char() == "=":
        p.parse("=")
        opt = _parse_arg(target, opt_type, p, vars_)
    else:
        opt = default_arg(target, opt_type)
    return UnionArg(typ, opt)


def _eat_excessive(p: _Parser, stop_at_comma: bool) -> None:
    """Eat excess args/fields to recover after description changes
    (reference: prog/encoding.go:507-548)."""
    paren = brack = brace = 0
    while not p.eof():
        ch = p.char()
        if ch == "(":
            paren += 1
        elif ch == ")":
            if paren == 0:
                return
            paren -= 1
        elif ch == "[":
            brack += 1
        elif ch == "]":
            if brack == 0:
                return
            brack -= 1
        elif ch == "{":
            brace += 1
        elif ch == "}":
            if brace == 0:
                return
            brace -= 1
        elif ch == ",":
            if stop_at_comma and paren == 0 and brack == 0 and brace == 0:
                return
        elif ch in "'\"":
            p.parse(ch)
            while not p.eof() and p.char() != ch:
                p.parse(p.char())
            if p.eof():
                return
        p.parse(ch)


def _deserialize_data(p: _Parser) -> bytes:
    data = bytearray()
    if p.char() == '"':
        p.parse('"')
        val = ""
        if p.char() != '"':
            val = p.ident()
        p.parse('"')
        try:
            data = bytearray(binascii.unhexlify(val))
        except binascii.Error:
            raise ParseError(f"data arg has bad value {val!r}")
    else:
        if p.consume() != "'":
            raise ParseError("data arg does not start with \" nor with '")
        unescape = {"a": 7, "b": 8, "f": 12, "n": 10, "r": 13, "t": 9,
                    "v": 11, "'": 0x27, "\\": 0x5C}
        while not p.eof() and p.char() != "'":
            v = p.consume()
            if v != "\\":
                data.append(ord(v))
                continue
            v = p.consume()
            if v == "x":
                hi = p.consume()
                lo = p.consume()
                if lo != "0" or hi != "0":
                    raise ParseError(
                        f"invalid \\x{hi}{lo} escape sequence in data arg")
                data.append(0)
            elif v in unescape:
                data.append(unescape[v])
            else:
                raise ParseError(f"invalid \\{v} escape sequence in data arg")
        p.parse("'")
    return bytes(data)


def call_set(data: bytes) -> set[str]:
    """Conservative call-name extraction from any serialization
    (reference: prog/encoding.go:836-869)."""
    calls: set[str] = set()
    for ln in data.decode("latin-1", errors="replace").splitlines():
        if not ln or ln.startswith("#"):
            continue
        bracket = ln.find("(")
        if bracket == -1:
            raise ParseError("line does not contain opening bracket")
        call = ln[:bracket]
        if "=" in call:
            call = call.split("=", 1)[1].strip()
        call = call.strip()
        if not call:
            raise ParseError("call name is empty")
        calls.add(call)
    if not calls:
        raise ParseError("program does not contain any calls")
    return calls
