"""Program minimization: greedy call removal then per-arg
simplification, each step re-validated by an equivalence predicate
(usually: re-execution keeps the signal / still crashes)
(reference: prog/minimization.go:14-210)."""

from __future__ import annotations

from typing import Callable

from syzkaller_tpu.models.prog import (
    Arg,
    Call,
    ConstArg,
    DataArg,
    GroupArg,
    PointerArg,
    Prog,
    ResultArg,
    UnionArg,
    remove_arg,
)
from syzkaller_tpu.models.size import assign_sizes_call
from syzkaller_tpu.models.types import (
    ArrayKind,
    ArrayType,
    BufferKind,
    BufferType,
    ConstType,
    CsumType,
    Dir,
    FlagsType,
    IntType,
    LenType,
    ProcType,
    PtrType,
    ResourceType,
    StructType,
    UnionType,
    VmaType,
)

Pred = Callable[[Prog, int], bool]


def minimize(p0: Prog, call_index0: int, crash: bool, pred0: Pred) -> tuple[Prog, int]:
    """(reference: prog/minimization.go:14-61)"""
    from syzkaller_tpu.models import validation

    if validation.debug:
        def pred(p: Prog, ci: int) -> bool:
            validation.validate_prog(p)
            return pred0(p, ci)
    else:
        pred = pred0

    name0 = ""
    if call_index0 != -1:
        assert 0 <= call_index0 < len(p0.calls), "bad call index"
        name0 = p0.calls[call_index0].meta.name

    p0, call_index0 = _remove_calls(p0, call_index0, crash, pred)

    for i in range(len(p0.calls)):
        ctx = _MinimizeArgsCtx(p0, call_index0, crash, pred)
        while True:
            p = ctx.p0.clone()
            call = p.calls[i]
            restart = False
            for j, arg in enumerate(call.args):
                if ctx.do(p, call, arg, str(j)):
                    restart = True
                    break
            if not restart:
                break
        p0 = ctx.p0

    if call_index0 != -1:
        assert 0 <= call_index0 < len(p0.calls) and \
            name0 == p0.calls[call_index0].meta.name, \
            "bad call index after minimization"
    return p0, call_index0


def _remove_calls(p0: Prog, call_index0: int, crash: bool, pred: Pred) -> tuple[Prog, int]:
    for i in range(len(p0.calls) - 1, -1, -1):
        if i == call_index0:
            continue
        call_index = call_index0
        if i < call_index:
            call_index -= 1
        p = p0.clone()
        p.remove_call(i)
        if not pred(p, call_index):
            continue
        p0 = p
        call_index0 = call_index
    return p0, call_index0


class _MinimizeArgsCtx:
    def __init__(self, p0: Prog, call_index0: int, crash: bool, pred: Pred):
        self.p0 = p0
        self.call_index0 = call_index0
        self.crash = crash
        self.pred = pred
        self.tried_paths: set[str] = set()

    def do(self, p: Prog, call: Call, arg: Arg, path: str) -> bool:
        """(reference: prog/minimization.go:91-210)"""
        path += f"-{arg.typ.field_name}"
        t = arg.typ
        if isinstance(t, StructType):
            assert isinstance(arg, GroupArg)
            return any(self.do(p, call, inner, path) for inner in arg.inner)
        if isinstance(t, UnionType):
            assert isinstance(arg, UnionArg)
            return self.do(p, call, arg.option, path)
        if isinstance(t, PtrType):
            if not isinstance(arg, PointerArg):
                return False
            if arg.res is not None:
                return self.do(p, call, arg.res, path)
            return False
        if isinstance(t, ArrayType):
            assert isinstance(arg, GroupArg)
            for i, inner in enumerate(list(arg.inner)):
                inner_path = f"{path}-{i}"
                if inner_path not in self.tried_paths and not self.crash:
                    if (t.kind == ArrayKind.RANGE_LEN
                            and len(arg.inner) > t.range_begin) \
                            or t.kind == ArrayKind.RAND_LEN:
                        arg.inner.pop(i)
                        remove_arg(inner)
                        assign_sizes_call(call)
                        if self.pred(p, self.call_index0):
                            self.p0 = p
                        else:
                            self.tried_paths.add(inner_path)
                        return True
                if self.do(p, call, inner, inner_path):
                    return True
            return False
        if isinstance(t, (IntType, FlagsType, ProcType)):
            if self.crash or path in self.tried_paths:
                return False
            self.tried_paths.add(path)
            assert isinstance(arg, ConstArg)
            if arg.val == t.default():
                return False
            v0 = arg.val
            arg.val = t.default()
            if self.pred(p, self.call_index0):
                self.p0 = p
                return True
            arg.val = v0
            return False
        if isinstance(t, ResourceType):
            if self.crash or path in self.tried_paths:
                return False
            self.tried_paths.add(path)
            assert isinstance(arg, ResultArg)
            if arg.res is None:
                return False
            r0 = arg.res
            arg.res = None
            arg.val = t.default()
            if self.pred(p, self.call_index0):
                self.p0 = p
                return True
            arg.res = r0
            arg.val = 0
            return False
        if isinstance(t, BufferType):
            if path in self.tried_paths:
                return False
            self.tried_paths.add(path)
            if t.kind not in (BufferKind.BLOB_RAND, BufferKind.BLOB_RANGE) \
                    or t.dir == Dir.OUT:
                return False
            assert isinstance(arg, DataArg)
            min_len = t.range_begin
            step = len(arg.data) - min_len
            while len(arg.data) > min_len and step > 0:
                if len(arg.data) - step >= min_len:
                    saved = bytes(arg.data)
                    arg.data = arg.data[:len(arg.data) - step]
                    assign_sizes_call(call)
                    if self.pred(p, self.call_index0):
                        continue
                    arg.data = bytearray(saved)
                    assign_sizes_call(call)
                step //= 2
                if self.crash:
                    break
            self.p0 = p
            return False
        if isinstance(t, (VmaType, LenType, CsumType, ConstType)):
            return False
        raise TypeError(f"unknown arg type {t!r}")
