"""Target: an OS/arch pair with its syscall/resource/struct tables.

Mirrors the reference target registry (reference: prog/target.go:14-153)
with lazy cross-reference wiring and resource-constructor discovery
(reference: prog/resources.go:10-130).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Callable, Optional

from syzkaller_tpu.models.types import (
    ConstValue,
    Dir,
    ResourceDesc,
    ResourceType,
    Syscall,
    Type,
    foreach_type,
)
from syzkaller_tpu.models.prog import Call, default_arg


@dataclass
class Target:
    os: str = "test"
    arch: str = "64"
    revision: str = ""
    ptr_size: int = 8
    page_size: int = 4096
    num_pages: int = 4096
    data_offset: int = 0x20000000

    syscalls: list[Syscall] = dc_field(default_factory=list)
    resources: list[ResourceDesc] = dc_field(default_factory=list)
    consts: list[ConstValue] = dc_field(default_factory=list)

    # Arch hooks (reference: prog/target.go:28-45).
    make_mmap: Optional[Callable[[int, int], Call]] = None
    sanitize_call: Callable[[Call], None] = lambda c: None
    special_types: dict[str, Callable] = dc_field(default_factory=dict)
    string_dictionary: list[str] = dc_field(default_factory=list)

    # Filled by _init:
    syscall_map: dict[str, Syscall] = dc_field(default_factory=dict)
    const_map: dict[str, int] = dc_field(default_factory=dict)
    resource_map: dict[str, ResourceDesc] = dc_field(default_factory=dict)
    resource_ctors: dict[str, list[Syscall]] = dc_field(default_factory=dict)
    _initialized: bool = False

    def init(self) -> "Target":
        if self._initialized:
            return self
        self._initialized = True
        self.const_map = {c.name: c.value for c in self.consts}
        self.resource_map = {r.name: r for r in self.resources}
        for i, c in enumerate(self.syscalls):
            c.id = i
            self.syscall_map[c.name] = c
            # Wire resource descriptors into resource types
            # (reference: prog/target.go:127-145).
            def wire(t: Type) -> None:
                if isinstance(t, ResourceType) and t.desc is None:
                    desc = self.resource_map.get(t.name)
                    if desc is None:
                        raise ValueError(f"no resource desc for {t.name}")
                    t.desc = desc
            foreach_type(c, wire)
        for r in self.resources:
            self.resource_ctors[r.name] = self.calc_resource_ctors(r.kind, False)
        return self

    # -- resources (reference: prog/resources.go) ------------------------

    def calc_resource_ctors(self, kind: tuple[str, ...], precise: bool) -> list[Syscall]:
        """Find calls with an out/inout arg (or ret) of the given resource
        kind (reference: prog/resources.go:10-32)."""
        metas: list[Syscall] = []
        for meta in self.syscalls:
            found = False

            def check(t: Type) -> None:
                nonlocal found
                if found or not isinstance(t, ResourceType):
                    return
                if t.dir != Dir.IN and t.desc is not None and \
                        is_compatible_resource_impl(kind, t.desc.kind, precise):
                    found = True

            foreach_type(meta, check)
            if found:
                metas.append(meta)
        return metas

    def is_compatible_resource(self, dst: str, src: str) -> bool:
        """True if a resource of kind src can be passed where dst is
        expected (reference: prog/resources.go:35-50)."""
        if dst in ("ANYRES16", "ANYRES32", "ANYRES64"):
            # Squashed resources accept anything
            # (reference: prog/resources.go:36-40).
            return True
        dst_res = self.resource_map.get(dst)
        src_res = self.resource_map.get(src)
        if dst_res is None:
            raise KeyError(f"unknown resource {dst!r}")
        if src_res is None:
            raise KeyError(f"unknown resource {src!r}")
        return is_compatible_resource_impl(dst_res.kind, src_res.kind, False)

    def input_resources(self, c: Syscall) -> list[ResourceType]:
        """Non-optional, non-out resource args of a call
        (reference: prog/resources.go:75-86)."""
        out: list[ResourceType] = []

        def collect(t: Type) -> None:
            if isinstance(t, ResourceType) and t.dir != Dir.OUT and not t.optional:
                out.append(t)

        foreach_type(c, collect)
        return out

    def transitively_enabled_calls(
        self, enabled: dict[Syscall, bool]
    ) -> tuple[dict[Syscall, bool], dict[Syscall, str]]:
        """Fixpoint: drop calls whose required input resources have no
        enabled precise constructor (reference: prog/resources.go:88-153)."""
        supported = {c for c, ok in enabled.items() if ok}
        inputs = {c: self.input_resources(c) for c in supported}
        ctors: dict[str, list[Syscall]] = {}
        for c in supported:
            for res in inputs[c]:
                assert res.desc is not None
                if res.desc.name not in ctors:
                    ctors[res.desc.name] = self.calc_resource_ctors(res.desc.kind, True)
        disabled: dict[Syscall, str] = {}
        while True:
            n = len(supported)
            for c in list(supported):
                for res in inputs[c]:
                    assert res.desc is not None
                    if not any(ct in supported for ct in ctors[res.desc.name]):
                        supported.discard(c)
                        names = [ct.name for ct in ctors[res.desc.name]]
                        disabled[c] = (
                            f"no syscalls can create resource {res.desc.name},"
                            f" enable some syscalls that can create it {names}")
                        break
            if n == len(supported):
                break
        return {c: True for c in supported}, disabled

    def default_arg(self, t: Type):
        return default_arg(self, t)

    def physical_addr(self, arg) -> int:
        """Fake physical address of a pointer arg
        (reference: prog/encodingexec.go:194-199)."""
        if arg.is_null():
            return 0
        return self.data_offset + arg.address


def is_compatible_resource_impl(dst: tuple[str, ...], src: tuple[str, ...],
                                precise: bool) -> bool:
    """Prefix-compare the two kind chains; when precise, a less
    specialized src cannot stand in for a more specialized dst
    (reference: prog/resources.go:52-73)."""
    dst = tuple(dst)
    src = tuple(src)
    if len(dst) > len(src):
        if precise:
            return False
        dst = dst[: len(src)]
    if len(src) > len(dst):
        src = src[: len(dst)]
    return dst == src


_targets: dict[str, Target] = {}
_lazy_targets: dict[str, object] = {}


def register_target(target: Target) -> None:
    key = f"{target.os}/{target.arch}"
    if key in _targets:
        raise ValueError(f"duplicate target {key}")
    _targets[key] = target


def register_lazy_target(os: str, arch: str, factory) -> None:
    """Register a target constructed on first GetTarget (used by the
    description pipeline so importing the package doesn't compile every
    shipped OS; reference analogue: generated sys/<os>/gen tables are
    wired by init() but prog.Target init is lazy, prog/target.go:80)."""
    key = f"{os}/{arch}"
    if key in _targets:
        raise ValueError(f"duplicate target {key}")
    _lazy_targets[key] = factory


def is_registered(os: str, arch: str) -> bool:
    key = f"{os}/{arch}"
    return key in _targets or key in _lazy_targets


def get_target(os: str, arch: str) -> Target:
    key = f"{os}/{arch}"
    t = _targets.get(key)
    if t is None:
        # Auto-register built-in targets on first use.
        import syzkaller_tpu.sys  # noqa: F401

        t = _targets.get(key)
    if t is None and key in _lazy_targets:
        # Pop only on success: a factory that raises (e.g. description
        # compile error) must stay registered so retries surface the
        # real error rather than a KeyError.
        t = _lazy_targets[key]()
        _targets[key] = t
        del _lazy_targets[key]
    if t is None:
        raise KeyError(
            f"unknown target {key} "
            f"(have: {sorted(set(_targets) | set(_lazy_targets))})")
    return t.init()
