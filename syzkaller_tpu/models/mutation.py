"""Program mutation: the CPU semantics engine for hot loop #1.

A weighted loop of five ops — squash-to-blob, corpus splice, call
insertion, arg mutation, call removal — with the byte-level mutate_data
engine underneath (reference: prog/mutation.go:14-521).  The batched
TPU implementation of the same distributions lives in ops/mutate.py and
is parity-tested against this module.
"""

from __future__ import annotations

from typing import Optional

from syzkaller_tpu.models.analysis import analyze
from syzkaller_tpu.models.prog import (
    Arg,
    ArgCtx,
    Call,
    ConstArg,
    DataArg,
    GroupArg,
    PointerArg,
    Prog,
    ResultArg,
    UnionArg,
    foreach_arg,
    foreach_sub_arg,
    replace_arg,
    remove_arg,
)
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.generation import (
    alloc_addr,
    create_resource,
    generate_arg,
    generate_call,
)
from syzkaller_tpu.models.size import assign_sizes_call, mutate_size
from syzkaller_tpu.models.types import (
    ArrayKind,
    ArrayType,
    BufferKind,
    BufferType,
    ConstType,
    CsumType,
    Dir,
    FlagsType,
    IntType,
    LenType,
    ProcType,
    PtrType,
    ResourceType,
    StructType,
    TextKind,
    UnionType,
    VmaType,
)
from syzkaller_tpu.utils.ints import MASK64, load_int, store_int, swap_int

MAX_BLOB_LEN = 100 << 10


def mutate_prog(p: Prog, rng: RandGen, ncalls: int, ct=None,
                corpus: Optional[list[Prog]] = None,
                ops_out: Optional[list[str]] = None) -> None:
    """(reference: prog/mutation.go:14-142)

    ops_out, when given, records the name of every op that landed —
    the observable for distribution-parity tests."""
    corpus = corpus or []
    target = p.target
    stop = False
    retry = False
    while not stop or retry:
        retry = False
        if rng.one_of(5):
            op, ok = "squash", _op_squash(p, rng, ct)
        elif rng.n_out_of(1, 100):
            op, ok = "splice", _op_splice(p, rng, ncalls, corpus)
        elif rng.n_out_of(20, 31):
            op, ok = "insert", _op_insert(p, rng, ncalls, ct)
        elif rng.n_out_of(10, 11):
            op, ok = "mutate_arg", _op_mutate_arg(p, rng, ct)
        else:
            op, ok = "remove", _op_remove(p, rng)
        if not ok:
            retry = True
            continue
        if ops_out is not None:
            ops_out.append(op)
        stop = rng.one_of(3)

    for c in p.calls:
        target.sanitize_call(c)


def _op_squash(p: Prog, rng: RandGen, ct) -> bool:
    """Squash a complex pointee into an ANY blob and mutate raw bytes
    (reference: prog/mutation.go:23-59)."""
    from syzkaller_tpu.models.any_squash import complex_ptrs, squash_ptr, is_any_ptr

    target = p.target
    ptrs = complex_ptrs(p)
    if not ptrs:
        return False
    ptr = ptrs[rng.intn(len(ptrs))]
    if not is_any_ptr(target, ptr.typ):
        squash_ptr(target, p, ptr, preserve_field=True)
    blobs: list[DataArg] = []
    bases: list[PointerArg] = []

    def collect(arg, ctx) -> None:
        if isinstance(arg, DataArg) and arg.typ.dir != Dir.OUT:
            blobs.append(arg)
            bases.append(ctx.base)

    foreach_sub_arg(ptr, collect)
    if not blobs:
        return False
    idx = rng.intn(len(blobs))
    arg, base = blobs[idx], bases[idx]
    base_size = base.res.size()
    arg.data = bytearray(mutate_data(rng, arg.data, 0, MAX_BLOB_LEN))
    # Update base pointer if the object grew.
    if base_size < base.res.size():
        s = analyze(ct, p, p.calls[0])
        new_arg = alloc_addr(rng, s, base.typ, base.res.size(), base.res)
        base.address = new_arg.address
    return True


def _op_splice(p: Prog, rng: RandGen, ncalls: int,
               corpus: list[Prog]) -> bool:
    """Splice a random corpus program in at a random position
    (reference: prog/mutation.go:61-71)."""
    if not corpus or not p.calls:
        return False
    p0 = corpus[rng.intn(len(corpus))]
    p0c = p0.clone()
    idx = rng.intn(len(p.calls))
    p.calls = p.calls[:idx] + p0c.calls + p.calls[idx:]
    for i in range(len(p.calls) - 1, ncalls - 1, -1):
        p.remove_call(i)
    return True


def _op_insert(p: Prog, rng: RandGen, ncalls: int, ct) -> bool:
    """Insert a generated call at a biased-random position
    (reference: prog/mutation.go:73-95)."""
    if len(p.calls) >= ncalls:
        return False
    idx = rng.biased_rand(len(p.calls) + 1, 5)
    c = p.calls[idx] if idx < len(p.calls) else None
    s = analyze(ct, p, c)
    calls = generate_call(rng, s, p)
    p.insert_before(c, calls)
    return True


def _op_mutate_arg(p: Prog, rng: RandGen, ct) -> bool:
    """Mutate args of a random call, repeating until a 1/3 stop coin
    (reference: prog/mutation.go:97-124)."""
    target = p.target
    if not p.calls:
        return False
    c = p.calls[rng.intn(len(p.calls))]
    if not c.args:
        return False
    s = analyze(ct, p, c)
    update_sizes = [True]
    stop_arg = False
    retry_arg = False
    while not stop_arg or retry_arg:
        retry_arg = False
        ma = MutationArgs(target)
        foreach_arg(c, ma.collect)
        if not ma.args:
            return False
        idx = rng.intn(len(ma.args))
        arg, ctx = ma.args[idx], ma.ctxes[idx]
        calls, ok = mutate_arg(rng, s, arg, ctx, update_sizes)
        if not ok:
            retry_arg = True
            continue
        p.insert_before(c, calls)
        if update_sizes[0]:
            assign_sizes_call(c)
        target.sanitize_call(c)
        stop_arg = rng.one_of(3)
    return True


def _op_remove(p: Prog, rng: RandGen) -> bool:
    """Remove a random call (reference: prog/mutation.go:126-131)."""
    if not p.calls:
        return False
    p.remove_call(rng.intn(len(p.calls)))
    return True


class MutationArgs:
    """Collects mutable args of a call (reference: prog/mutation.go:345-392)."""

    def __init__(self, target, ignore_special: bool = False):
        self.target = target
        self.args: list[Arg] = []
        self.ctxes: list[ArgCtx] = []
        self.ignore_special = ignore_special

    def collect(self, arg: Arg, ctx: ArgCtx) -> None:
        ignore_special = self.ignore_special
        self.ignore_special = False
        typ = arg.typ
        if isinstance(typ, StructType):
            if self.target.special_types.get(typ.name) is None or ignore_special:
                return  # for plain structs only individual fields are mutated
            ctx.stop = True
        elif isinstance(typ, UnionType):
            if (self.target.special_types.get(typ.name) is None
                    and len(typ.fields) == 1) or ignore_special:
                return
            ctx.stop = True
        elif isinstance(typ, ArrayType):
            # Don't mutate fixed-size arrays.
            if typ.kind == ArrayKind.RANGE_LEN and typ.range_begin == typ.range_end:
                return
        elif isinstance(typ, CsumType):
            return  # updated when the checksummed data changes
        elif isinstance(typ, ConstType):
            return
        elif isinstance(typ, BufferType):
            if typ.kind == BufferKind.STRING and len(typ.values) == 1:
                return  # string const
        elif isinstance(typ, PtrType):
            if isinstance(arg, PointerArg) and arg.is_null():
                return
        if typ is None or typ.dir == Dir.OUT or (not typ.varlen and typ.size() == 0):
            return
        self.args.append(arg)
        self.ctxes.append(ctx)


def mutate_arg(rng: RandGen, s, arg: Arg, ctx: ArgCtx,
               update_sizes: list[bool]) -> tuple[list[Call], bool]:
    """(reference: prog/mutation.go:144-165)"""
    target = rng.target
    base_size = ctx.base.res.size() if ctx.base is not None else 0
    calls, retry, preserve = _mutate_by_type(rng, s, arg, ctx)
    if retry:
        return [], False
    if preserve:
        update_sizes[0] = False
    if ctx.base is not None and base_size < ctx.base.res.size():
        new_arg = alloc_addr(rng, s, ctx.base.typ, ctx.base.res.size(), ctx.base.res)
        replace_arg(ctx.base, new_arg)
    for c in calls:
        target.sanitize_call(c)
    return calls, True


def _regenerate(rng: RandGen, s, arg: Arg) -> tuple[list[Call], bool, bool]:
    new_arg, calls = generate_arg(rng, s, arg.typ)
    replace_arg(arg, new_arg)
    return calls, False, False


def _mutate_int_value(rng: RandGen, s, arg: Arg) -> tuple[list[Call], bool, bool]:
    """(reference: prog/mutation.go:174-188)"""
    if rng.bin():
        return _regenerate(rng, s, arg)
    assert isinstance(arg, ConstArg)
    if rng.n_out_of(1, 3):
        arg.val = (arg.val + rng.intn(4) + 1) & MASK64
    elif rng.n_out_of(1, 2):
        arg.val = (arg.val - rng.intn(4) - 1) & MASK64
    else:
        arg.val ^= 1 << rng.intn(64)
    return [], False, False


def _mutate_by_type(rng: RandGen, s, arg: Arg, ctx: ArgCtx) -> tuple[list[Call], bool, bool]:
    """Per-type mutators (reference: prog/mutation.go:190-343).
    Returns (new_calls, retry, preserve)."""
    typ = arg.typ
    target = rng.target

    if isinstance(typ, (IntType, FlagsType)):
        return _mutate_int_value(rng, s, arg)

    if isinstance(typ, LenType):
        assert ctx.parent is not None
        if not mutate_size(rng, arg, ctx.parent):
            return [], True, False
        return [], False, True  # preserve: don't reassign sizes

    if isinstance(typ, (ResourceType, VmaType, ProcType)):
        return _regenerate(rng, s, arg)

    if isinstance(typ, BufferType):
        assert isinstance(arg, DataArg)
        if typ.kind in (BufferKind.BLOB_RAND, BufferKind.BLOB_RANGE):
            min_len, max_len = 0, MAX_BLOB_LEN
            if typ.kind == BufferKind.BLOB_RANGE:
                min_len, max_len = typ.range_begin, typ.range_end
            arg.data = bytearray(mutate_data(rng, bytearray(arg.data), min_len, max_len))
        elif typ.kind == BufferKind.STRING:
            if rng.bin():
                min_len, max_len = 0, MAX_BLOB_LEN
                if typ.type_size != 0:
                    min_len = max_len = typ.type_size
                arg.data = bytearray(mutate_data(rng, bytearray(arg.data), min_len, max_len))
            else:
                arg.data = bytearray(rng.rand_string(s, typ))
        elif typ.kind == BufferKind.FILENAME:
            arg.data = bytearray(rng.filename(s, typ).encode("latin-1"))
        elif typ.kind == BufferKind.TEXT:
            arg.data = bytearray(rng.mutate_text(typ.text, bytes(arg.data)))
        else:
            raise TypeError(f"unknown buffer kind {typ.kind}")
        return [], False, False

    if isinstance(typ, ArrayType):
        assert isinstance(arg, GroupArg) and typ.elem is not None
        count = len(arg.inner)
        if typ.kind == ArrayKind.RAND_LEN:
            while count == len(arg.inner):
                count = rng.rand_array_len()
        else:
            assert typ.range_begin != typ.range_end, "mutating fixed-length array"
            while count == len(arg.inner):
                count = rng.rand_range(typ.range_begin, typ.range_end)
        calls: list[Call] = []
        if count > len(arg.inner):
            while count > len(arg.inner):
                new_arg, new_calls = generate_arg(rng, s, typ.elem)
                arg.inner.append(new_arg)
                calls.extend(new_calls)
                for c in new_calls:
                    s.analyze(c)
        else:
            for extra in arg.inner[count:]:
                remove_arg(extra)
            del arg.inner[count:]
        return calls, False, False

    if isinstance(typ, PtrType):
        assert isinstance(arg, PointerArg)
        new_arg = alloc_addr(rng, s, typ, arg.res.size(), arg.res)
        replace_arg(arg, new_arg)
        return [], False, False

    if isinstance(typ, StructType):
        gen = target.special_types.get(typ.name)
        assert gen is not None, "plain struct returned by MutationArgs"
        from syzkaller_tpu.models.gen_api import Gen

        new_arg, calls = gen(Gen(rng, s), typ, arg)
        assert isinstance(arg, GroupArg) and isinstance(new_arg, GroupArg)
        for old, new in zip(arg.inner, new_arg.inner):
            replace_arg(old, new)
        return calls, False, False

    if isinstance(typ, UnionType):
        gen = target.special_types.get(typ.name)
        if gen is not None:
            from syzkaller_tpu.models.gen_api import Gen

            new_arg, calls = gen(Gen(rng, s), typ, arg)
            replace_arg(arg, new_arg)
            return calls, False, False
        assert isinstance(arg, UnionArg)
        current = -1
        for i, option in enumerate(typ.fields):
            if arg.option.typ.field_name == option.field_name:
                current = i
                break
        assert current >= 0, "can't find current option in union"
        new_idx = rng.intn(len(typ.fields) - 1)
        if new_idx >= current:
            new_idx += 1
        opt_type = typ.fields[new_idx]
        remove_arg(arg.option)
        new_opt, calls = generate_arg(rng, s, opt_type)
        replace_arg(arg, UnionArg(typ, new_opt))
        return calls, False, False

    raise TypeError(f"type {typ} can't be mutated")


# -- byte-level data mutation -------------------------------------------

MAX_INC = 35


def mutate_data(rng: RandGen, data: bytearray, min_len: int, max_len: int) -> bytearray:
    """Repeatedly apply one of 7 byte-level ops until a successful op
    lands and a 1/3 coin says stop (reference: prog/mutation.go:394-400)."""
    stop = False
    while not stop:
        f = _MUTATE_DATA_FUNCS[rng.intn(len(_MUTATE_DATA_FUNCS))]
        data, ok = f(rng, data, min_len, max_len)
        stop = ok and rng.one_of(3)
    return data


def _md_flip_bit(rng, data, min_len, max_len):
    if not data:
        return data, False
    byt = rng.intn(len(data))
    bit = rng.intn(8)
    data[byt] ^= 1 << bit
    return data, True


def _md_insert_bytes(rng, data, min_len, max_len):
    if not data or len(data) >= max_len:
        return data, False
    n = min(rng.intn(16) + 1, max_len - len(data))
    pos = rng.intn(len(data))
    new = bytes(rng.int31() & 0xFF for _ in range(n))
    orig_len = len(data)
    data[pos:pos] = new
    if rng.bin():
        del data[orig_len:]  # preserve original length
    return data, True


def _md_remove_bytes(rng, data, min_len, max_len):
    if len(data) <= min_len:
        return data, False
    n = min(rng.intn(16) + 1, len(data))
    pos = 0
    if n < len(data):
        pos = rng.intn(len(data) - n)
    del data[pos:pos + n]
    if rng.bin():
        data.extend(bytes(n))  # preserve original length
    return data, True


def _md_append_bytes(rng, data, min_len, max_len):
    if len(data) >= max_len:
        return data, False
    max_append = 256
    n = min(max_append - rng.biased_rand(max_append, 10), max_len - len(data))
    data.extend(rng.rand(256) for _ in range(n))
    return data, True


def _md_replace_int(rng, data, min_len, max_len):
    width = 1 << rng.intn(4)
    if len(data) < width:
        return data, False
    i = rng.intn(len(data) - width + 1)
    store_int(data, i, rng.uint64(), width)
    return data, True


def _md_add_sub_int(rng, data, min_len, max_len):
    width = 1 << rng.intn(4)
    if len(data) < width:
        return data, False
    i = rng.intn(len(data) - width + 1)
    v = load_int(data, i, width)
    delta = rng.rand(2 * MAX_INC + 1) - MAX_INC
    if delta == 0:
        delta = 1
    if rng.one_of(10):
        v = swap_int(v, width)
        v = (v + delta) & MASK64
        v = swap_int(v, width)
    else:
        v = (v + delta) & MASK64
    store_int(data, i, v, width)
    return data, True


def _md_interesting_int(rng, data, min_len, max_len):
    width = 1 << rng.intn(4)
    if len(data) < width:
        return data, False
    i = rng.intn(len(data) - width + 1)
    value = rng.rand_int()
    if rng.one_of(10):
        value = swap_int(value, 8)
    store_int(data, i, value, width)
    return data, True


_MUTATE_DATA_FUNCS = (
    _md_flip_bit,
    _md_insert_bytes,
    _md_remove_bytes,
    _md_append_bytes,
    _md_replace_int,
    _md_add_sub_int,
    _md_interesting_int,
)
