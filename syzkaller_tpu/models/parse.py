"""Fuzzer console-log parsing: recover executed programs from output.

Splits a fuzzer/VM console log into entries at "executing program"
markers and deserializes the program text that follows each — the
input to reproducer extraction (reference: prog/parse.go:22 ParseLog,
markers logged by syz-fuzzer/proc.go:255-262).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional

from syzkaller_tpu.models.encoding import ParseError, deserialize_prog
from syzkaller_tpu.models.prog import Prog

# "executing program 3:" / "executing program 3 (fault-call:2 fault-nth:5):"
_MARKER_RE = re.compile(
    rb"executing program (\d+)"
    rb"(?: \(fault-call:(\d+) fault-nth:(\d+)\))?:?")


@dataclass
class LogEntry:
    """(reference: prog/parse.go LogEntry)"""
    p: Prog
    proc: int = 0
    start: int = 0
    end: int = 0
    fault_call: int = -1
    fault_nth: int = 0


def parse_log(target, data: bytes) -> list[LogEntry]:
    """(reference: prog/parse.go:22-86)"""
    entries: list[LogEntry] = []
    pos = 0
    cur: Optional[tuple[int, int, int, int]] = None  # start,proc,fc,fn
    lines: list[tuple[int, bytes]] = []
    for m in re.finditer(rb"[^\n]*\n?", data):
        lines.append((m.start(), m.group(0)))

    def flush(end: int) -> None:
        nonlocal cur
        if cur is None:
            return
        start, proc, fc, fn = cur
        body = data[start:end]
        # program text starts after the marker line
        nl = body.find(b"\n")
        text = body[nl + 1:] if nl >= 0 else b""
        text = _strip_log_prefixes(text)
        if text.strip():
            try:
                p = deserialize_prog(target, text)
                if len(p.calls):
                    entries.append(LogEntry(p=p, proc=proc, start=start,
                                            end=end, fault_call=fc,
                                            fault_nth=fn))
            except ParseError:
                pass
        cur = None

    for off, line in lines:
        m = _MARKER_RE.search(line)
        if m is not None:
            flush(off)
            cur = (off, int(m.group(1)),
                   int(m.group(2)) if m.group(2) else -1,
                   int(m.group(3)) if m.group(3) else 0)
    flush(len(data))
    return entries


def _strip_log_prefixes(text: bytes) -> bytes:
    """Drop console noise lines; keep only plausible program lines.
    The deserializer additionally tolerates unknown calls/args."""
    out = []
    for line in text.splitlines():
        s = line.strip()
        if not s:
            break  # blank line ends the program block
        # program lines look like "r0 = call(...)" or "call(...)" or
        # continuation of a long line
        if re.match(rb"^(r\d+ = )?[a-zA-Z_][a-zA-Z0-9_$]*\(", s) \
                or s.startswith(b"#"):
            out.append(line)
        else:
            break
    return b"\n".join(out) + b"\n" if out else b""
