"""ANY-squashing: flatten a typed pointee tree into a raw-blob union
that preserves resource references, enabling byte-soup mutation of
complex structures (reference: prog/any.go:7-334).

The squashed form maps onto the TPU program tensor directly: data
elements become arena spans, resource elements stay as slot refs.
"""

from __future__ import annotations

from syzkaller_tpu.models.prog import (
    Arg,
    ConstArg,
    DataArg,
    GroupArg,
    PointerArg,
    Prog,
    ResultArg,
    UnionArg,
    foreach_arg,
    foreach_sub_arg,
)
from syzkaller_tpu.models.types import (
    ArrayType,
    BufferType,
    CsumType,
    Dir,
    IntType,
    PtrType,
    ResourceDesc,
    ResourceType,
    StructType,
    Type,
    UnionType,
    is_pad,
)
from syzkaller_tpu.utils.ints import MASK64, swap_int


class AnyTypes:
    """Synthetic ANY type family, one instance per target
    (reference: prog/any.go:18-111)."""

    def __init__(self, target):
        self.union = UnionType(name="ANYUNION", field_name="ANYUNION",
                               varlen=True, dir=Dir.IN)
        self.array = ArrayType(name="ANYARRAY", field_name="ANYARRAY",
                               varlen=True, elem=self.union)
        self.ptr_ptr = PtrType(name="ptr", field_name="ANYPTR",
                               type_size=target.ptr_size, optional=True,
                               elem=self.array)
        self.ptr64 = PtrType(name="ptr64", field_name="ANYPTR64",
                             type_size=8, optional=True, elem=self.array)
        self.blob = BufferType(name="ANYBLOB", field_name="ANYBLOB", varlen=True)

        def res(name: str, base: str, size: int) -> ResourceType:
            return ResourceType(
                name=name, field_name=name, dir=Dir.IN, type_size=size,
                optional=True,
                desc=ResourceDesc(name=name, kind=(name,),
                                  values=(MASK64, 0),
                                  type=IntType(name=base, type_size=size)))

        self.res16 = res("ANYRES16", "int16", 2)
        self.res32 = res("ANYRES32", "int32", 4)
        self.res64 = res("ANYRES64", "int64", 8)
        self.union.fields = [self.blob, self.ptr_ptr, self.ptr64,
                             self.res16, self.res32, self.res64]


def get_any(target) -> AnyTypes:
    any_ = getattr(target, "_any_types", None)
    if any_ is None:
        any_ = AnyTypes(target)
        target._any_types = any_
    return any_


def make_any_ptr_type(target, size: int, field: str) -> PtrType:
    any_ = get_any(target)
    base = any_.ptr_ptr if size == target.ptr_size else any_.ptr64
    assert size in (target.ptr_size, 8), f"bad pointer size {size}"
    t = PtrType(name=base.name, field_name=field or base.field_name,
                type_size=size, optional=True, elem=any_.array)
    return t


def is_any_ptr(target, typ: Type) -> bool:
    return isinstance(typ, PtrType) and typ.elem is get_any(target).array


def complex_ptrs(p: Prog) -> list[PointerArg]:
    """Pointers to squashable (structurally complex) objects
    (reference: prog/any.go:136-146)."""
    res: list[PointerArg] = []
    for c in p.calls:
        def visit(arg, ctx) -> None:
            if isinstance(arg, PointerArg) and is_complex_ptr(p.target, arg):
                res.append(arg)
                ctx.stop = True

        foreach_arg(c, visit)
    return res


def is_complex_ptr(target, arg: PointerArg) -> bool:
    """(reference: prog/any.go:148-175)"""
    if arg.res is None or arg.typ.dir != Dir.IN:
        return False
    if is_any_ptr(target, arg.typ):
        return True
    res = [False]

    def visit(a1, ctx) -> None:
        t = a1.typ
        if isinstance(t, StructType):
            if t.varlen:
                res[0] = True
                ctx.stop = True
        elif isinstance(t, UnionType):
            if t.varlen and len(t.fields) > 5:
                res[0] = True
                ctx.stop = True
        elif isinstance(t, PtrType):
            if a1 is not arg:
                ctx.stop = True

    foreach_sub_arg(arg.res, visit)
    return res[0]


def call_contains_any(target, c) -> bool:
    found = [False]

    def visit(arg, ctx) -> None:
        if is_any_ptr(target, arg.typ):
            found[0] = True
            ctx.stop = True

    foreach_arg(c, visit)
    return found[0]


def squash_ptr(target, p: Prog, arg: PointerArg, preserve_field: bool) -> None:
    """(reference: prog/any.go:197-214)"""
    assert arg.res is not None and arg.vma_size == 0, "bad ptr arg"
    size0 = arg.res.size()
    elems: list[Arg] = []
    _squash_impl(target, arg.res, elems)
    field = arg.typ.field_name if preserve_field else ""
    arg.typ = make_any_ptr_type(target, arg.typ.size(), field)
    arg.res = GroupArg(arg.typ.elem, elems)
    assert arg.res.size() == size0, \
        f"squash changed size {size0}->{arg.res.size()}"


def _squash_impl(target, a: Arg, elems: list[Arg]) -> None:
    """(reference: prog/any.go:216-309)"""
    any_ = get_any(target)
    assert a.typ.bitfield_length() == 0, "bitfield in squash"
    pad = 0
    if isinstance(a, ConstArg):
        if is_pad(a.typ):
            pad = a.size()
        else:
            v = _squash_const(target, a)
            elem = _ensure_data_elem(target, elems)
            for _ in range(a.size()):
                elem.data.append(v & 0xFF)
                v >>= 8
    elif isinstance(a, ResultArg):
        size = a.size()
        a.typ = {2: any_.res16, 4: any_.res32, 8: any_.res64}[size]
        elems.append(UnionArg(any_.union, a))
    elif isinstance(a, PointerArg):
        if a.res is not None:
            squash_ptr(target, None, a, False)
            elems.append(UnionArg(any_.union, a))
        else:
            elem = _ensure_data_elem(target, elems)
            addr = target.physical_addr(a)
            for _ in range(a.size()):
                elem.data.append(addr & 0xFF)
                addr >>= 8
    elif isinstance(a, UnionArg):
        if not a.typ.varlen:
            pad = a.size() - a.option.size()
        _squash_impl(target, a.option, elems)
    elif isinstance(a, DataArg):
        if a.typ.dir == Dir.OUT:
            pad = a.size()
        else:
            elem = _ensure_data_elem(target, elems)
            elem.data.extend(a.data)
    elif isinstance(a, GroupArg):
        t = a.typ
        if isinstance(t, StructType) and t.varlen and t.align_attr != 0:
            fields_size = sum(f.size() for f in a.inner
                              if not f.typ.bitfield_middle())
            if fields_size % t.align_attr != 0:
                pad = t.align_attr - fields_size % t.align_attr
        bitfield = 0
        for fld in a.inner:
            bf_len = fld.typ.bitfield_length()
            if bf_len != 0:
                bf_off = fld.typ.bitfield_offset()
                v = _squash_const(target, fld)  # type: ignore[arg-type]
                bitfield |= (v & ((1 << bf_len) - 1)) << bf_off
                if not fld.typ.bitfield_middle():
                    elem = _ensure_data_elem(target, elems)
                    for _ in range(fld.size()):
                        elem.data.append(bitfield & 0xFF)
                        bitfield >>= 8
                    bitfield = 0
                continue
            _squash_impl(target, fld, elems)
    else:
        raise TypeError("bad arg kind in squash")
    if pad:
        elem = _ensure_data_elem(target, elems)
        elem.data.extend(bytes(pad))


def _squash_const(target, arg: ConstArg) -> int:
    if isinstance(arg.typ, CsumType):
        # Can't compute checksums here; leave a recognizable marker
        # (reference: prog/any.go:311-320).
        return 0xABCDEF1234567890
    v, stride, be = arg.value()
    # pid 0 materialization
    if be:
        v = swap_int(v, arg.size())
    return v


def _ensure_data_elem(target, elems: list[Arg]) -> DataArg:
    any_ = get_any(target)
    if elems:
        last = elems[-1]
        assert isinstance(last, UnionArg)
        if isinstance(last.option, DataArg):
            return last.option
    res = DataArg(any_.blob, b"")
    elems.append(UnionArg(any_.union, res))
    return res
