"""Per-worker fuzzing loop (reference: syz-fuzzer/proc.go).

Each Proc owns one executor Env (fork-server) and runs the weighted
loop: dequeue prioritized work, else 1-in-N generate from scratch,
else mutate a corpus program.  Mutants come either from the CPU
mutator (reference semantics) or from a shared BatchMutator that
drains pre-computed device batches — the feed/drain integration of
the TPU engine (SURVEY.md §7 step 8).
"""

from __future__ import annotations

import threading
from typing import Optional

from syzkaller_tpu.fuzzer.fuzzer import Fuzzer, Stat, signal_prio
from syzkaller_tpu.fuzzer.workqueue import (
    ProgTypes,
    WorkCandidate,
    WorkSmash,
    WorkTriage,
)
from syzkaller_tpu.ipc.env import (
    CallFlags,
    Env,
    ExecFlags,
    ExecOpts,
    ExecResult,
    ExecutorCrash,
    ExecutorFailure,
)
from syzkaller_tpu.models.encoding import serialize_prog
from syzkaller_tpu.models.encodingexec import serialize_for_exec
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.hints import CompMap, mutate_with_hints
from syzkaller_tpu.models.minimization import minimize
from syzkaller_tpu.models.mutation import mutate_prog
from syzkaller_tpu.models.prog import Prog
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.signal import Signal, from_raw
from syzkaller_tpu.signal.cover import Cover
from syzkaller_tpu.utils import log


class BatchMutator:
    """Feed/drain queue between procs and the device mutation engine.

    Procs call next() for a single mutant; when the buffer runs dry the
    calling proc refills it with one engine batch over a random corpus
    sample.  Amortizes host⇄device transfer over batch_size mutants
    while other procs keep their executors saturated (SURVEY.md §7
    hard part (c))."""

    def __init__(self, engine, batch_size: int = 64):
        self.engine = engine
        self.batch_size = batch_size
        self._buf: list[Prog] = []
        self._lock = threading.Lock()

    def next(self, fuzzer: Fuzzer, rng: RandGen) -> Optional[Prog]:
        with self._lock:
            if self._buf:
                return self._buf.pop()
        corpus_items = fuzzer.corpus_snapshot()
        if not corpus_items:
            return None
        templates = []
        for _ in range(self.batch_size):
            item = corpus_items[rng.intn(len(corpus_items))]
            t = self.engine.encode(item.p)
            if t is not None:
                templates.append(t)
        if not templates:
            return None
        mutants = self.engine.mutate(
            templates, ct=fuzzer.ct, corpus=[it.p for it in corpus_items])
        with self._lock:
            self._buf.extend(m for m in mutants if m is not None)
            if not self._buf:
                return None
            return self._buf.pop()


class Proc:
    """One worker: an Env + a seeded RNG + the loop
    (reference: syz-fuzzer/proc.go:28-64)."""

    def __init__(self, fuzzer: Fuzzer, pid: int, env: Env,
                 rng: Optional[RandGen] = None,
                 batch_mutator: Optional[BatchMutator] = None):
        self.fuzzer = fuzzer
        self.pid = pid
        self.env = env
        self.rng = rng or RandGen(fuzzer.target, pid * 1103515245 + 12345)
        self.batch_mutator = batch_mutator
        self.exec_opts = ExecOpts(flags=ExecFlags(0))
        self.exec_opts_cover = ExecOpts(flags=ExecFlags.COLLECT_COVER
                                        | ExecFlags.DEDUP_COVER)
        self.exec_opts_comps = ExecOpts(flags=ExecFlags.COLLECT_COMPS)
        self.last_prog: Optional[Prog] = None
        self._corpus_cache: list[Prog] = []
        # Console program logging: on under a manager/VM (enables
        # crash→repro), off standalone to keep the hot loop lean.
        self.log_programs = fuzzer.conn is not None

    # -- main loop --------------------------------------------------------

    def loop(self, iterations: int = 1 << 62,
             stop: Optional[threading.Event] = None) -> None:
        """(reference: proc.go:66-98)"""
        cfg = self.fuzzer.cfg
        for i in range(iterations):
            if stop is not None and stop.is_set():
                return
            item = self.fuzzer.wq.dequeue()
            if item is not None:
                if isinstance(item, WorkTriage):
                    self.triage_input(item)
                elif isinstance(item, WorkCandidate):
                    self.execute(self.exec_opts, item.p, Stat.CANDIDATE,
                                 flags=item.flags)
                elif isinstance(item, WorkSmash):
                    self.smash_input(item)
                continue
            if self.fuzzer.corpus_len() == 0 \
                    or self.rng.one_of(cfg.generate_period):
                p = generate_prog(self.fuzzer.target, self.rng,
                                  cfg.program_length, ct=self.fuzzer.ct)
                self.execute(self.exec_opts, p, Stat.GENERATE)
            else:
                p = self._next_mutant()
                if p is None:
                    continue
                self.execute(self.exec_opts, p, Stat.FUZZ)

    def _next_mutant(self) -> Optional[Prog]:
        if self.batch_mutator is not None:
            p = self.batch_mutator.next(self.fuzzer, self.rng)
            if p is not None:
                return p
        base = self.fuzzer.choose_corpus_prog(self.rng)
        if base is None:
            return None
        p = base.clone()
        # The corpus only grows; refresh the splice-source cache only
        # when it has (the snapshot is an O(n) copy under the lock).
        if len(self._corpus_cache) != self.fuzzer.corpus_len():
            self._corpus_cache = [
                it.p for it in self.fuzzer.corpus_snapshot()]
        mutate_prog(p, self.rng, self.fuzzer.cfg.program_length,
                    ct=self.fuzzer.ct, corpus=self._corpus_cache)
        return p

    # -- triage ----------------------------------------------------------

    def triage_input(self, item: WorkTriage) -> None:
        """Deflake + minimize a new-signal find, land it in the corpus
        (reference: proc.go:100-181)."""
        cfg = self.fuzzer.cfg
        call_index = item.call_index
        input_signal = item.signal
        new_signal = self.fuzzer.corpus_signal_diff(input_signal)
        if new_signal.empty():
            return
        call_name = item.p.calls[call_index].meta.name
        log.logf(3, "triaging %s (new signal %d)", call_name, len(new_signal))

        # Compute the flakiness-stable subset over triage_runs re-runs
        # (flake intersection, proc.go:120-140).
        notexecuted = 0
        input_cover = Cover()
        stable = new_signal
        for _ in range(cfg.triage_runs):
            info = self.execute_raw(self.exec_opts_cover, item.p,
                                    Stat.TRIAGE)
            ci = _find_call(info, call_index)
            if ci is None:
                notexecuted += 1
                if notexecuted > cfg.triage_runs / 2:
                    return  # the call does not reproduce
                continue
            prio = signal_prio(item.p, ci.errno, call_index)
            this_signal = from_raw(ci.signal, prio)
            stable = stable.intersection(this_signal)
            if stable.empty():
                return
            input_cover.merge(ci.cover)
        input_signal = stable

        if not item.flags.minimized:
            def pred(p: Prog, ci_idx: int) -> bool:
                for _ in range(cfg.minimize_attempts):
                    info = self.execute_raw(self.exec_opts, p, Stat.MINIMIZE)
                    ci = _find_call(info, ci_idx)
                    if ci is None:
                        continue
                    prio = signal_prio(p, ci.errno, ci_idx)
                    this_signal = from_raw(ci.signal, prio)
                    if len(input_signal.intersection(this_signal)) \
                            == len(input_signal):
                        return True
                return False

            item.p, call_index = minimize(item.p, call_index, False, pred)

        data = serialize_prog(item.p)
        corpus_item = self.fuzzer.add_input_to_corpus(
            item.p, input_signal, input_cover, serialized=data)
        if corpus_item is not None:
            self.fuzzer.send_input_to_manager(corpus_item, call_index)
        if not item.flags.smashed:
            self.fuzzer.wq.enqueue(WorkSmash(item.p, call_index))

    # -- smash -----------------------------------------------------------

    def smash_input(self, item: WorkSmash) -> None:
        """Aggressive exploration of a fresh corpus input: hints pass,
        fault injection, extra mutants (reference: proc.go:183-228)."""
        cfg = self.fuzzer.cfg
        if cfg.collect_comps:
            self.execute_hint_seed(item.p, item.call_index)
        if cfg.fault_injection:
            self.fail_call(item.p, item.call_index)
        corpus = [it.p for it in self.fuzzer.corpus_snapshot()]
        for _ in range(cfg.smash_mutants):
            p = item.p.clone()
            mutate_prog(p, self.rng, cfg.program_length,
                        ct=self.fuzzer.ct, corpus=corpus)
            self.execute(self.exec_opts, p, Stat.SMASH)

    def fail_call(self, p: Prog, call_index: int) -> None:
        """Inject a fault into each of the first fault_nth_max blocking
        points of the call (reference: proc.go:199-211)."""
        for nth in range(1, self.fuzzer.cfg.fault_nth_max + 1):
            opts = ExecOpts(flags=ExecFlags.FAULT,
                            fault_call=call_index, fault_nth=nth)
            info = self.execute_raw(opts, p, Stat.SMASH)
            ci = _find_call(info, call_index)
            if ci is not None and not (ci.flags & CallFlags.FAULT_INJECTED):
                break  # no more blocking points

    def execute_hint_seed(self, p: Prog, call_index: int) -> None:
        """Collect comparison operands for the call, then execute every
        hint mutant (reference: proc.go:213-228)."""
        info = self.execute_raw(self.exec_opts_comps, p, Stat.SEED)
        ci = _find_call(info, call_index)
        if ci is None or not ci.comps:
            return
        comps = CompMap()
        for op1, op2 in ci.comps:
            comps.add_comp(op1, op2)

        def exec_cb(mutant: Prog) -> None:
            self.execute(self.exec_opts, mutant, Stat.HINT)

        mutate_with_hints(p, call_index, comps, exec_cb)

    # -- execution --------------------------------------------------------

    def execute(self, opts: ExecOpts, p: Prog, stat: Stat,
                flags: Optional[ProgTypes] = None) -> Optional[ExecResult]:
        """Execute + novelty check; new signal enqueues triage work
        (reference: proc.go:230-247)."""
        result = self.execute_raw(opts, p, stat)
        if result is None:
            return None
        for call_index, sig in self.fuzzer.check_new_signal(p, result.info):
            self.fuzzer.wq.enqueue(WorkTriage(
                p=p.clone(), call_index=call_index, signal=sig,
                flags=flags or ProgTypes(minimized=False, smashed=False),
                from_candidate=flags is not None))
        return result

    def execute_raw(self, opts: ExecOpts, p: Prog,
                    stat: Stat) -> Optional[ExecResult]:
        """(reference: proc.go:249-277 incl. crash/retry handling)"""
        self.fuzzer.stat_add(stat)
        self.fuzzer.stat_add(Stat.EXEC_TOTAL)
        self.last_prog = p
        # Log every executed program to the console: this is both the
        # liveness marker scanned by monitor_execution and the data
        # source for reproducer extraction via parse_log
        # (reference: proc.go:249-262 logProgram).
        if self.log_programs:
            marker = f"executing program {self.pid}"
            if opts.fault_call >= 0:
                marker += (f" (fault-call:{opts.fault_call}"
                           f" fault-nth:{opts.fault_nth})")
            from syzkaller_tpu.models.encoding import serialize_prog

            log.logf(0, "%s:\n%s", marker,
                     serialize_prog(p).decode())
        data = serialize_for_exec(p)
        try:
            result = self.env.exec(opts, data)
        except ExecutorCrash as e:
            self.fuzzer.record_crash(e.log, p)
            return None
        except ExecutorFailure as e:
            log.logf(1, "proc %d: executor failure: %s", self.pid, e)
            self.fuzzer.stat_add(Stat.EXECUTOR_RESTARTS)
            return None
        return result


def _find_call(result: Optional[ExecResult], call_index: int):
    if result is None:
        return None
    for ci in result.info:
        if ci.call_index == call_index:
            return ci
    return None
