"""Per-worker fuzzing loop (reference: syz-fuzzer/proc.go).

Each Proc owns one executor Env (fork-server) and runs the weighted
loop: dequeue prioritized work, else 1-in-N generate from scratch,
else mutate a corpus program.  Mutants come either from the CPU
mutator (reference semantics) or from a shared PipelineMutator that
drains exec-ready mutant batches off the device-resident corpus
pipeline — the feed/drain integration of the TPU engine (SURVEY.md §7
step 8; reference shape: syz-fuzzer/proc.go:66-98).
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Union

from syzkaller_tpu import telemetry
from syzkaller_tpu.telemetry import lineage
from syzkaller_tpu.fuzzer.fuzzer import Fuzzer, Stat, signal_prio
from syzkaller_tpu.fuzzer.workqueue import (
    ProgTypes,
    WorkCandidate,
    WorkSmash,
    WorkTriage,
)
from syzkaller_tpu.ipc.env import (
    CallFlags,
    Env,
    ExecFlags,
    ExecOpts,
    ExecResult,
    ExecutorCrash,
    ExecutorFailure,
)
from syzkaller_tpu.models.encoding import serialize_prog
from syzkaller_tpu.models.encodingexec import serialize_for_exec
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.hints import CompMap, mutate_with_hints
from syzkaller_tpu.models.minimization import minimize
from syzkaller_tpu.models.mutation import mutate_prog
from syzkaller_tpu.models.prog import Prog
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.signal import Signal, from_raw
from syzkaller_tpu.signal.cover import Cover
from syzkaller_tpu.utils import log

# Poll-loop telemetry (docs/observability.md): iteration count plus
# span-timed phases — executor round-trips (proc.exec), triage passes
# (proc.triage), and waits on the device pipeline (proc.device_wait).
_M_LOOP_ITERS = telemetry.counter(
    "tz_proc_loop_iterations_total", "proc fuzz-loop iterations")

#: Workqueue lane per execution Stat, for per-source novelty
#: attribution (telemetry/coverage.py SOURCES): the generate/mutate
#: fallback is the "exploration" lane, candidate executions the
#: "candidate" lane, smash-phase executions (extra mutants, hints,
#: fault injection seeds) the "smash" lane, and triage re-executions
#: (deflake/minimize) the "triage"/"triage_candidate" lanes.
_LANE_BY_STAT = {
    Stat.GENERATE: "exploration",
    Stat.FUZZ: "exploration",
    Stat.CANDIDATE: "candidate",
    Stat.TRIAGE: "triage",
    Stat.MINIMIZE: "triage",
    Stat.SMASH: "smash",
    Stat.HINT: "hints",
    Stat.SEED: "smash",
}


class PipelineMutator:
    """Integrated mutation source over a DevicePipeline
    (ops/pipeline.py): each draw runs the REFERENCE op ladder
    (reference: prog/mutation.go:19-131).  The device classes —
    insert (donor-bank splice with ChoiceTable sampling, ~51% of
    iterations), arg-mutate and remove, together ~79% of iteration
    weight — route to the device ring, which produces exec-ready
    mutants with the same conditional class split on device; the
    remaining structural classes (squash, splice) run the CPU op on a
    cloned base, and a failed op redraws the full ladder — exactly
    the reference's retry shape, so the landed-op distribution is
    success-conditioned the same way the reference's is.

    next() returns either an exec-ready ExecMutant or a typed Prog;
    Proc.execute handles both.  An ExecMutant's exec_bytes is a
    zero-copy view into its batch's output arena (ops/emit), written
    straight into the executor's shmem by Env.exec — the draw path
    never copies mutant bytes.  Corpus growth is fed to the device
    ring on every draw (one scatter per pipeline step).

    Health latch: after demote_after CONSECUTIVE drain timeouts — or
    the moment the pipeline's circuit breaker reports open
    (syzkaller_tpu/health/breaker.py), which detects the same wedge
    from the worker side without burning drain_timeout waits — the
    mutator latches to "demoted": device draws return None instantly
    (Proc falls back to CPU mutation within the same draw) instead of
    serializing every proc on drain_timeout waits against a wedged
    device (the axon-tunnel failure mode).  A background probe keeps
    polling the pipeline and clears the latch the moment the device
    answers again.  Demotions/re-promotions and the pipeline's
    breaker/watchdog transitions are drained into Stat counters so
    the manager status page shows them."""

    def __init__(self, pipeline, drain_timeout: float = 60.0,
                 demote_after: int = 3, probe_interval: float = 5.0,
                 probe_timeout: Optional[float] = None):
        self.pipeline = pipeline
        self.drain_timeout = drain_timeout
        self.demote_after = demote_after
        self.probe_interval = probe_interval
        self.probe_timeout = (drain_timeout if probe_timeout is None
                              else probe_timeout)
        self._lock = threading.Lock()
        self._fed = 0
        self._corpus_cache: list[Prog] = []
        self._consec_timeouts = 0
        self._demoted = threading.Event()
        self._stash = None  # mutant recovered by the health probe
        self._probe_thread: Optional[threading.Thread] = None
        # Health transition counters (drained into Stat counters by
        # _sync_health_stats so the manager sees them).
        self.demotions = 0
        self.repromotions = 0
        self._reported: dict[str, int] = {}
        # Tests set this to a list to observe the op-class stream.
        self.ops_journal: Optional[list[str]] = None

    # -- health latch -----------------------------------------------------

    def healthy(self) -> bool:
        return not self._demoted.is_set()

    def health_snapshot(self) -> dict:
        """Latch + pipeline breaker/watchdog state (including the
        assembly pool's worker count and queue depth, which the
        pipeline folds into its own snapshot), for tests and status
        surfaces."""
        out = {"demoted": self._demoted.is_set(),
               "demotions": self.demotions,
               "repromotions": self.repromotions}
        snap = getattr(self.pipeline, "health_snapshot", None)
        if callable(snap):
            out["pipeline"] = snap()
        return out

    def _demote(self, reason: str) -> None:
        # One mutator is shared by every proc thread: the latch set
        # and probe spawn must be atomic or two threads can both pass
        # the gate and spawn duplicate probes.
        with self._lock:
            if self._demoted.is_set():
                return
            self._demoted.set()
            self.demotions += 1
            t = threading.Thread(target=self._probe_loop, daemon=True,
                                 name="pipeline-health-probe")
            self._probe_thread = t
        log.logf(0, "DEVICE PIPELINE DEMOTED: %s; falling back to CPU "
                    "mutation (background probe will re-enable)", reason)
        t.start()

    def _note_drain_timeout(self) -> None:
        with self._lock:
            self._consec_timeouts += 1
            n = self._consec_timeouts
        if n < self.demote_after:
            return
        self._demote(f"{n} consecutive {self.drain_timeout:.0f}s "
                     "drain timeouts")

    def _probe_loop(self) -> None:
        while self._demoted.is_set():
            pstop = getattr(self.pipeline, "_stop", None)
            if pstop is not None and pstop.is_set():
                return  # pipeline shut down; stay demoted
            m = self.pipeline.next(timeout=self.probe_timeout)
            if m is not None:
                with self._lock:
                    self._stash = m
                    self._consec_timeouts = 0
                    self.repromotions += 1
                    self._demoted.clear()
                log.logf(0, "device pipeline answering again; "
                            "re-enabling device mutation")
                return
            time.sleep(self.probe_interval)

    def _sync_health_stats(self, fuzzer: Fuzzer) -> None:
        """Drain monotonic health counters (mutator latch + pipeline
        breaker/watchdog + co-resident triage engine) into the
        fuzzer's poll-synced Stat deltas."""
        pstats = getattr(self.pipeline, "stats", None)
        br = getattr(self.pipeline, "breaker", None)
        wd = getattr(self.pipeline, "watchdog", None)
        te = getattr(self.pipeline, "triage_engine", None)
        with self._lock:
            totals = {
                Stat.DEVICE_DEMOTIONS: self.demotions,
                Stat.DEVICE_REPROMOTIONS: self.repromotions,
            }
            if pstats is not None:
                totals[Stat.DEVICE_WORKER_ERRORS] = pstats.worker_errors
            if br is not None:
                totals[Stat.DEVICE_BREAKER_OPENS] = br.counters.opens
                totals[Stat.DEVICE_REBUILDS] = br.counters.rebuilds
            if wd is not None:
                totals[Stat.DEVICE_WEDGES] = wd.stats.wedges
            if te is not None:
                totals[Stat.DEVICE_TRIAGE_DEMOTIONS] = te.stats.demotions
                totals[Stat.DEVICE_TRIAGE_REPROMOTIONS] = \
                    te.stats.repromotions
            if pstats is not None and getattr(
                    pstats, "sim_batches", 0):
                totals[Stat.DEVICE_SIM_BATCHES] = pstats.sim_batches
                totals[Stat.DEVICE_SIM_SUPPRESSED] = \
                    pstats.sim_suppressed
            deltas = []
            for stat, total in totals.items():
                seen = self._reported.get(stat.name, 0)
                if total > seen:
                    self._reported[stat.name] = total
                    deltas.append((stat, total - seen))
        for stat, d in deltas:
            fuzzer.stat_add(stat, d)

    def _sync_corpus(self, fuzzer: Fuzzer) -> list[Prog]:
        """Feed new corpus items to the device ring; returns the
        splice-source snapshot."""
        if fuzzer.corpus_len() == self._fed:
            return self._corpus_cache
        with self._lock:
            items = fuzzer.corpus_snapshot()
            new = items[self._fed:]
            self._fed = len(items)
            self._corpus_cache = [it.p for it in items]
            cache = self._corpus_cache
        for it in new:
            self.pipeline.add(it.p)
        return cache

    def next(self, fuzzer: Fuzzer,
             rng: RandGen) -> Optional[Union[Prog, "object"]]:
        from syzkaller_tpu.models.mutation import (
            _op_splice,
            _op_squash,
            mutate_prog,
        )

        corpus = self._sync_corpus(fuzzer)
        if len(self.pipeline) == 0:
            return None
        base = fuzzer.choose_corpus_prog(rng)
        if base is None:
            return None
        ncalls = fuzzer.cfg.program_length
        ct = fuzzer.ct
        p: Optional[Prog] = None
        while True:
            # The reference op ladder (prog/mutation.go:19-131); the
            # insert/arg-mutate/remove tail is one "device" outcome —
            # the kernel draws insert-vs-mutate per mutant on device
            # (ops/pipeline step: P_INSERT_GIVEN_DEVICE; arg/remove at
            # 10/11-vs-1/11 per round in ops/mutate._mutate_one).
            if rng.one_of(5):
                op = "squash"
            elif rng.n_out_of(1, 100):
                op = "splice"
            else:
                op = "device"
            if op == "device":
                self._sync_health_stats(fuzzer)
                br = getattr(self.pipeline, "breaker", None)
                if br is not None and not self._demoted.is_set() \
                        and br.is_open():
                    # The pipeline worker's breaker detected the wedge
                    # from its side: demote immediately instead of
                    # burning demote_after drain-timeout waits
                    # rediscovering it from the proc side.
                    self._demote(f"device circuit breaker {br.state}")
                if self._demoted.is_set():
                    return None  # health latch: CPU fallback in Proc
                with self._lock:
                    m, self._stash = self._stash, None
                if m is None:
                    with telemetry.span("proc.device_wait"):
                        m = self.pipeline.next(timeout=self.drain_timeout)
                if m is None:
                    self._note_drain_timeout()
                    return None
                with self._lock:
                    # Reset under the lock: a racing _note_drain_timeout
                    # must not overwrite this and demote one draw early.
                    self._consec_timeouts = 0
                if self.ops_journal is not None:
                    self.ops_journal.append("device")
                fuzzer.stat_add(Stat.DEVICE_MUTANTS)
                # Lineage: the first draw off a sampled batch records
                # its prefetch-queue wait (one hop per batch — the
                # context is shared by every mutant of the batch).
                tr = getattr(m, "trace", None)
                if tr is not None and tr.last_stage != "proc.draw":
                    lineage.hop(tr, "proc.draw")
                return m
            if p is None:
                p = base.clone()
            if op == "squash":
                ok = _op_squash(p, rng, ct)
            else:
                ok = _op_splice(p, rng, ncalls, corpus)
            if not ok:
                continue  # reference retry: redraw the full ladder
            if self.ops_journal is not None:
                self.ops_journal.append(op)
            if not rng.one_of(3):
                # Continue coin: further iterations run the full CPU
                # reference loop (may mix in any op class, as the
                # reference would).
                mutate_prog(p, rng, ncalls, ct=ct, corpus=corpus,
                            ops_out=self.ops_journal)
            else:
                for c in p.calls:
                    fuzzer.target.sanitize_call(c)
            return p


class Proc:
    """One worker: an Env + a seeded RNG + the loop
    (reference: syz-fuzzer/proc.go:28-64)."""

    def __init__(self, fuzzer: Fuzzer, pid: int, env: Env,
                 rng: Optional[RandGen] = None,
                 mutator: Optional[PipelineMutator] = None,
                 device_hints: bool = False,
                 hint_lane=None):
        self.fuzzer = fuzzer
        self.pid = pid
        self.env = env
        self.rng = rng or RandGen(fuzzer.target, pid * 1103515245 + 12345)
        self.mutator = mutator
        # Smash's hint pass runs the batched shrinkExpand kernel
        # (ops/hints.py) instead of the per-window CPU walk.
        self.device_hints = device_hints
        # The shared fleet-wide lane (ops/hintlane.HintLane) wins over
        # the per-program device path: comps staged cross-proc, one
        # fused kernel per flush, lane="hints" accounting.
        self.hint_lane = hint_lane
        self.exec_opts = ExecOpts(flags=ExecFlags(0))
        self.exec_opts_cover = ExecOpts(flags=ExecFlags.COLLECT_COVER
                                        | ExecFlags.DEDUP_COVER)
        self.exec_opts_comps = ExecOpts(flags=ExecFlags.COLLECT_COMPS)
        self.last_prog: Optional[Prog] = None
        self._corpus_cache: list[Prog] = []
        # Console program logging: on under a manager/VM (enables
        # crash→repro), off standalone to keep the hot loop lean.
        self.log_programs = fuzzer.conn is not None

    # -- main loop --------------------------------------------------------

    def loop(self, iterations: int = 1 << 62,
             stop: Optional[threading.Event] = None) -> None:
        """(reference: proc.go:66-98)"""
        cfg = self.fuzzer.cfg
        for i in range(iterations):
            if stop is not None and stop.is_set():
                return
            _M_LOOP_ITERS.inc()
            item = self.fuzzer.wq.dequeue()
            if item is not None:
                if isinstance(item, WorkTriage):
                    with telemetry.span("proc.triage"):
                        self.triage_input(item)
                elif isinstance(item, WorkCandidate):
                    self.execute(self.exec_opts, item.p, Stat.CANDIDATE,
                                 flags=item.flags)
                elif isinstance(item, WorkSmash):
                    self.smash_input(item)
                continue
            if self.fuzzer.corpus_len() == 0 \
                    or self.rng.one_of(cfg.generate_period):
                p = generate_prog(self.fuzzer.target, self.rng,
                                  cfg.program_length, ct=self.fuzzer.ct)
                self.execute(self.exec_opts, p, Stat.GENERATE)
            else:
                p = self._next_mutant()
                if p is None:
                    continue
                self.execute(self.exec_opts, p, Stat.FUZZ)

    def _next_mutant(self):
        if self.mutator is not None:
            p = self.mutator.next(self.fuzzer, self.rng)
            if p is not None:
                return p
        base = self.fuzzer.choose_corpus_prog(self.rng)
        if base is None:
            return None
        p = base.clone()
        # The corpus only grows; refresh the splice-source cache only
        # when it has (the snapshot is an O(n) copy under the lock).
        if len(self._corpus_cache) != self.fuzzer.corpus_len():
            self._corpus_cache = [
                it.p for it in self.fuzzer.corpus_snapshot()]
        mutate_prog(p, self.rng, self.fuzzer.cfg.program_length,
                    ct=self.fuzzer.ct, corpus=self._corpus_cache)
        return p

    # -- triage ----------------------------------------------------------

    def triage_input(self, item: WorkTriage) -> None:
        """Deflake + minimize a new-signal find, land it in the corpus
        (reference: proc.go:100-181)."""
        cfg = self.fuzzer.cfg
        call_index = item.call_index
        input_signal = item.signal
        new_signal = self.fuzzer.corpus_signal_diff(input_signal)
        if new_signal.empty():
            return
        call_name = item.p.calls[call_index].meta.name
        log.logf(3, "triaging %s (new signal %d)", call_name, len(new_signal))

        # Compute the flakiness-stable subset over triage_runs re-runs
        # (flake intersection, proc.go:120-140).
        notexecuted = 0
        input_cover = Cover()
        stable = new_signal
        for _ in range(cfg.triage_runs):
            info = self.execute_raw(self.exec_opts_cover, item.p,
                                    Stat.TRIAGE)
            ci = _find_call(info, call_index)
            if ci is None:
                notexecuted += 1
                if notexecuted > cfg.triage_runs / 2:
                    return  # the call does not reproduce
                continue
            prio = signal_prio(item.p, ci.errno, call_index)
            this_signal = from_raw(ci.signal, prio)
            stable = stable.intersection(this_signal)
            if stable.empty():
                return
            input_cover.merge(ci.cover)
        input_signal = stable

        if not item.flags.minimized:
            def pred(p: Prog, ci_idx: int) -> bool:
                for _ in range(cfg.minimize_attempts):
                    info = self.execute_raw(self.exec_opts, p, Stat.MINIMIZE)
                    ci = _find_call(info, ci_idx)
                    if ci is None:
                        continue
                    prio = signal_prio(p, ci.errno, ci_idx)
                    this_signal = from_raw(ci.signal, prio)
                    if len(input_signal.intersection(this_signal)) \
                            == len(input_signal):
                        return True
                return False

            item.p, call_index = minimize(item.p, call_index, False, pred)

        data = serialize_prog(item.p)
        corpus_item = self.fuzzer.add_input_to_corpus(
            item.p, input_signal, input_cover, serialized=data)
        if corpus_item is not None:
            # Lineage: the mutant's lifecycle terminus — it survived
            # deflake+minimize and landed in the corpus; the NewInput
            # frame carries the context to the manager side.
            lineage.hop(item.trace, "corpus.add")
            self.fuzzer.send_input_to_manager(corpus_item, call_index,
                                              trace=item.trace)
        if not item.flags.smashed:
            self.fuzzer.wq.enqueue(WorkSmash(item.p, call_index))

    # -- smash -----------------------------------------------------------

    def smash_input(self, item: WorkSmash) -> None:
        """Aggressive exploration of a fresh corpus input: hints pass,
        fault injection, extra mutants (reference: proc.go:183-228)."""
        cfg = self.fuzzer.cfg
        if cfg.collect_comps:
            self.execute_hint_seed(item.p, item.call_index)
        if cfg.fault_injection:
            self.fail_call(item.p, item.call_index)
        corpus = [it.p for it in self.fuzzer.corpus_snapshot()]
        for _ in range(cfg.smash_mutants):
            p = item.p.clone()
            mutate_prog(p, self.rng, cfg.program_length,
                        ct=self.fuzzer.ct, corpus=corpus)
            self.execute(self.exec_opts, p, Stat.SMASH)

    def fail_call(self, p: Prog, call_index: int) -> None:
        """Inject a fault into each of the first fault_nth_max blocking
        points of the call (reference: proc.go:199-211)."""
        for nth in range(1, self.fuzzer.cfg.fault_nth_max + 1):
            opts = ExecOpts(flags=ExecFlags.FAULT,
                            fault_call=call_index, fault_nth=nth)
            info = self.execute_raw(opts, p, Stat.SMASH)
            ci = _find_call(info, call_index)
            if ci is not None and not (ci.flags & CallFlags.FAULT_INJECTED):
                break  # no more blocking points

    def execute_hint_seed(self, p: Prog, call_index: int) -> None:
        """Collect comparison operands for the call, then execute every
        hint mutant (reference: proc.go:213-228)."""
        info = self.execute_raw(self.exec_opts_comps, p, Stat.SEED)
        ci = _find_call(info, call_index)
        if ci is None or not ci.comps:
            return
        comps = CompMap()
        for op1, op2 in ci.comps:
            comps.add_comp(op1, op2)

        def exec_cb(mutant: Prog) -> None:
            self.execute(self.exec_opts, mutant, Stat.HINT)

        if self.hint_lane is not None:
            self.hint_lane.run(p, call_index, comps, exec_cb)
        elif self.device_hints:
            from syzkaller_tpu.ops.hints import mutate_with_hints_device

            mutate_with_hints_device(p, call_index, comps, exec_cb)
        else:
            mutate_with_hints(p, call_index, comps, exec_cb)

    # -- execution --------------------------------------------------------

    def execute(self, opts: ExecOpts, p, stat: Stat,
                flags: Optional[ProgTypes] = None,
                source: Optional[str] = None) -> Optional[ExecResult]:
        """Execute + novelty check; new signal enqueues triage work
        (reference: proc.go:230-247).

        p is a typed Prog or an exec-ready device mutant (anything with
        .exec_bytes / .signal_prio / .prog()); mutants are decoded to a
        typed program only when they produce new signal — the ~1/1000
        triage path (syz-fuzzer/proc.go:100).

        `source` overrides the workqueue-lane attribution of any novel
        edges this execution confirms; by default the lane is derived
        from `stat` (_LANE_BY_STAT) and threaded — alongside the
        lineage context — through the TriageEngine verdict path into
        `tz_coverage_novel_edges_total{source=...}`."""
        result = self.execute_raw(opts, p, stat)
        if result is None:
            return None
        source = source or _LANE_BY_STAT.get(stat, "exploration")
        trace = None
        if _is_exec_mutant(p):
            trace = p.trace
            news = self.fuzzer.check_new_signal_fn(p.signal_prio,
                                                   result.info,
                                                   trace=trace,
                                                   source=source,
                                                   proc=self.pid)
            if not news:
                return result
            decoded = p.prog()  # lazy typed decode for triage
        else:
            news = self.fuzzer.check_new_signal(p, result.info,
                                                source=source,
                                                proc=self.pid)
            decoded = p
        for call_index, sig in news:
            self.fuzzer.wq.enqueue(WorkTriage(
                p=decoded.clone(), call_index=call_index, signal=sig,
                flags=flags or ProgTypes(minimized=False, smashed=False),
                from_candidate=flags is not None, trace=trace))
        return result

    def execute_raw(self, opts: ExecOpts, p,
                    stat: Stat) -> Optional[ExecResult]:
        """(reference: proc.go:249-277 incl. crash/retry handling)"""
        self.fuzzer.stat_add(stat)
        self.fuzzer.stat_add(Stat.EXEC_TOTAL)
        self.last_prog = p
        # Log every executed program to the console: this is both the
        # liveness marker scanned by monitor_execution and the data
        # source for reproducer extraction via parse_log
        # (reference: proc.go:249-262 logProgram).
        if self.log_programs:
            marker = f"executing program {self.pid}"
            if opts.fault_call >= 0:
                marker += (f" (fault-call:{opts.fault_call}"
                           f" fault-nth:{opts.fault_nth})")
            from syzkaller_tpu.models.encoding import serialize_prog

            typed = p.prog() if _is_exec_mutant(p) else p
            log.logf(0, "%s:\n%s", marker,
                     serialize_prog(typed).decode())
        if _is_exec_mutant(p):
            data = p.exec_bytes  # arena view, handed zero-copy to Env
        else:
            data = serialize_for_exec(p)
        try:
            with telemetry.span("proc.exec"):
                result = self.env.exec(opts, data)
        except ExecutorCrash as e:
            self.fuzzer.record_crash(
                e.log, p.prog() if _is_exec_mutant(p) else p)
            return None
        except ExecutorFailure as e:
            log.logf(1, "proc %d: executor failure: %s", self.pid, e)
            self.fuzzer.stat_add(Stat.EXECUTOR_RESTARTS)
            return None
        return result


def _is_exec_mutant(p) -> bool:
    """Duck-typed: keeps proc.py importable without jax (ExecMutant
    lives in ops/pipeline, which pulls in the device stack)."""
    return hasattr(p, "exec_bytes")


def _find_call(result: Optional[ExecResult], call_index: int):
    if result is None:
        return None
    for ci in result.info:
        if ci.call_index == call_index:
            return ci
    return None
