"""Prioritized work queue for fuzzing work items.

Priorities (highest first): triage of candidates > candidates > triage
of own finds > smash.  Rationale mirrors the reference: corpus
candidates from the manager carry externally-proven signal, so landing
them beats exploring locally (reference: syz-fuzzer/workqueue.go:17-125).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional

from syzkaller_tpu.models.prog import Prog


@dataclass
class ProgTypes:
    minimized: bool = True
    smashed: bool = True


@dataclass
class WorkTriage:
    """A program that produced new signal: deflake, minimize, add to
    corpus (reference: workqueue.go:38-48).  `trace` carries the
    originating mutant's lineage context (telemetry/lineage.py) so
    the corpus-add and manager NewInput hops stay on its track."""
    p: Prog
    call_index: int
    signal: object  # signal.Signal
    flags: ProgTypes = field(default_factory=ProgTypes)
    from_candidate: bool = False
    trace: Optional[object] = None


@dataclass
class WorkCandidate:
    """A corpus candidate from the manager that must be executed and
    triaged before joining the local corpus (workqueue.go:50-56)."""
    p: Prog
    flags: ProgTypes = field(default_factory=ProgTypes)


@dataclass
class WorkSmash:
    """A freshly-landed corpus input to explore aggressively: extra
    mutants, fault injection, hints (workqueue.go:58-63)."""
    p: Prog
    call_index: int


class WorkQueue:
    """Four priority bands + a wake event; procs fall back to
    generate/mutate when empty (reference: workqueue.go:65-125)."""

    def __init__(self, procs: int = 1):
        from collections import deque

        self._lock = threading.Lock()
        self._triage_candidate: deque = deque()
        self._candidate: deque = deque()
        self._triage: deque = deque()
        self._smash: deque = deque()
        # Backpressure bound on locally-generated smash items, scaled by
        # procs like the reference's wantCandidates heuristic.
        self.procs = procs

    def enqueue(self, item) -> None:
        with self._lock:
            if isinstance(item, WorkTriage):
                if item.from_candidate:
                    self._triage_candidate.append(item)
                else:
                    self._triage.append(item)
            elif isinstance(item, WorkCandidate):
                self._candidate.append(item)
            elif isinstance(item, WorkSmash):
                self._smash.append(item)
            else:  # pragma: no cover - programming error
                raise TypeError(f"unknown work item {item!r}")

    def dequeue(self):
        with self._lock:
            # FIFO within a band: oldest finds get triaged first
            # (reference consumes in arrival order, workqueue.go:90-99).
            for q in (self._triage_candidate, self._candidate,
                      self._triage, self._smash):
                if q:
                    return q.popleft()
        return None

    def want_candidates(self) -> bool:
        """Ask the manager for more candidates when the local queue is
        thin (reference: workqueue.go:101-104)."""
        with self._lock:
            return len(self._candidate) < self.procs

    def __len__(self) -> int:
        with self._lock:
            return (len(self._triage_candidate) + len(self._candidate)
                    + len(self._triage) + len(self._smash))
