"""Shared fuzzer state: corpus, signal sets, stats, manager link.

Reference: syz-fuzzer/fuzzer.go:31-95 (Fuzzer struct + stats),
424-521 (corpus/signal bookkeeping).  The manager connection is
optional — with conn=None the fuzzer runs standalone (the syz-stress
form factor) and keeps everything local.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Callable, Optional

from syzkaller_tpu import telemetry
from syzkaller_tpu.telemetry import lineage
from syzkaller_tpu.models.any_squash import call_contains_any
from syzkaller_tpu.models.encoding import serialize_prog
from syzkaller_tpu.models.prio import ChoiceTable, build_choice_table
from syzkaller_tpu.models.prog import Prog
from syzkaller_tpu.signal import Signal
from syzkaller_tpu.signal.cover import Cover
from syzkaller_tpu.utils.hashsig import hash_string
from syzkaller_tpu.utils import log


class Stat(IntEnum):
    """Per-fuzzer counters synced to the manager on every poll
    (reference: syz-fuzzer/fuzzer.go:63-86)."""
    GENERATE = 0
    FUZZ = 1
    CANDIDATE = 2
    TRIAGE = 3
    MINIMIZE = 4
    SMASH = 5
    HINT = 6
    SEED = 7
    EXEC_TOTAL = 8
    EXECUTOR_RESTARTS = 9
    CRASHES = 10
    DEVICE_MUTANTS = 11
    DEVICE_WORKER_ERRORS = 12
    # Self-healing runtime transitions (syzkaller_tpu/health): synced
    # to the manager so the status page shows engine health per fleet.
    DEVICE_DEMOTIONS = 13
    DEVICE_REPROMOTIONS = 14
    DEVICE_BREAKER_OPENS = 15
    DEVICE_REBUILDS = 16
    DEVICE_WEDGES = 17
    # Triage-engine health (syzkaller_tpu/triage): device-plane
    # novelty checks demoted to / re-promoted from the CPU path.
    DEVICE_TRIAGE_DEMOTIONS = 18
    DEVICE_TRIAGE_REPROMOTIONS = 19
    # Sim-exec prescore (syzkaller_tpu/sim): batches drained through
    # the speculative stage and plane-novel rows it held back.
    DEVICE_SIM_BATCHES = 20
    DEVICE_SIM_SUPPRESSED = 21


STAT_NAMES = {
    Stat.GENERATE: "exec gen",
    Stat.FUZZ: "exec fuzz",
    Stat.CANDIDATE: "exec candidate",
    Stat.TRIAGE: "exec triage",
    Stat.MINIMIZE: "exec minimize",
    Stat.SMASH: "exec smash",
    Stat.HINT: "exec hints",
    Stat.SEED: "exec seeds",
    Stat.EXEC_TOTAL: "exec total",
    Stat.EXECUTOR_RESTARTS: "executor restarts",
    Stat.CRASHES: "crashes",
    Stat.DEVICE_MUTANTS: "device mutants",
    Stat.DEVICE_WORKER_ERRORS: "device worker errors",
    Stat.DEVICE_DEMOTIONS: "device demotions",
    Stat.DEVICE_REPROMOTIONS: "device repromotions",
    Stat.DEVICE_BREAKER_OPENS: "device breaker opens",
    Stat.DEVICE_REBUILDS: "device ring rebuilds",
    Stat.DEVICE_WEDGES: "device wedges",
    Stat.DEVICE_TRIAGE_DEMOTIONS: "device triage demotions",
    Stat.DEVICE_TRIAGE_REPROMOTIONS: "device triage repromotions",
    Stat.DEVICE_SIM_BATCHES: "device sim prescored batches",
    Stat.DEVICE_SIM_SUPPRESSED: "device sim suppressed rows",
}


def _check_stat_names(stats_enum, names) -> None:
    """Stat <-> STAT_NAMES drift guard: adding a Stat member without a
    display name silently drops it from polls and the registry, so
    registration fails loudly instead."""
    missing = [s.name for s in stats_enum if s not in names]
    if missing:
        raise AssertionError(
            f"Stat members without a STAT_NAMES entry: {missing}")
    stale = [s for s in names if s not in list(stats_enum)]
    if stale:
        raise AssertionError(
            f"STAT_NAMES entries without a Stat member: {stale}")


def _stat_metric_name(display_name: str) -> str:
    """'device ring rebuilds' -> 'tz_fuzzer_device_ring_rebuilds_total'
    (tools/lint_metrics.py derives the same mapping from STAT_NAMES to
    cross-check the docs catalogue)."""
    return "tz_fuzzer_" + display_name.replace(" ", "_") + "_total"


_check_stat_names(Stat, STAT_NAMES)

#: Monotonic per-Stat registry counters: the poll-drained deltas in
#: Fuzzer.stats feed the manager; these feed /metrics and stay
#: monotonic across polls (one source of truth per surface).
_STAT_COUNTERS = {
    s: telemetry.counter(_stat_metric_name(STAT_NAMES[s]),
                         f"fuzzer stat: {STAT_NAMES[s]}")
    for s in Stat
}


def signal_prio(p: Prog, errno: int, call_index: int) -> int:
    """Priority of an edge observed for call call_index: +2 if the call
    succeeded, +1 if the call is a plain typed call (no squashed ANY
    blob) (reference: syz-fuzzer/fuzzer.go:513-521)."""
    prio = 0
    if errno == 0:
        prio |= 1 << 1
    if not call_contains_any(p.target, p.calls[call_index]):
        prio |= 1 << 0
    return prio


@dataclass
class FuzzerConfig:
    """Behavioral constants of the fuzz loop; defaults match the
    reference (syz-fuzzer/proc.go:26,116,191-228)."""
    program_length: int = 30
    generate_period: int = 100  # 1-in-N iterations generates from scratch
    triage_runs: int = 3  # signal deflake re-runs
    minimize_attempts: int = 3  # re-runs per minimize step
    smash_mutants: int = 100
    fault_injection: bool = True
    fault_nth_max: int = 100
    collect_comps: bool = True  # hints (KCOV_TRACE_CMP equivalent)
    leak_check: bool = False


@dataclass
class CorpusItem:
    p: Prog
    serialized: bytes
    sig: str
    signal: Signal
    cover: Cover = field(default_factory=Cover)


class Fuzzer:
    """Shared state across procs (reference: fuzzer.go:31-61)."""

    def __init__(self, target, wq, cfg: Optional[FuzzerConfig] = None,
                 ct: Optional[ChoiceTable] = None, conn=None,
                 on_crash: Optional[Callable[[str, Optional[Prog]], None]] = None,
                 triage=None):
        from syzkaller_tpu.fuzzer.workqueue import WorkQueue

        self.target = target
        self.cfg = cfg or FuzzerConfig()
        self.wq = wq if wq is not None else WorkQueue()
        self.conn = conn  # manager RPC client (optional)
        self.on_crash = on_crash
        self._lock = threading.Lock()
        self.corpus: list[CorpusItem] = []
        self.corpus_hashes: set[str] = set()
        self.corpus_signal = Signal()  # signal of corpus inputs
        self.max_signal = Signal()  # everything ever seen (incl. manager)
        self.new_signal = Signal()  # delta not yet reported to manager
        self.ct = ct or build_choice_table(target)
        self.stats = [0] * len(Stat)
        self._exec_total = 0
        # Optional device-plane novelty pre-filter (duck-typed so this
        # module stays importable without jax; syzkaller_tpu/triage).
        self.triage = None
        if triage is not None:
            self.set_triage(triage)

    # -- stats -----------------------------------------------------------

    def stat_add(self, s: Stat, v: int = 1) -> None:
        with self._lock:
            self.stats[s] += v
            if s == Stat.EXEC_TOTAL:
                self._exec_total += v
        # Registry mirror: monotonic (never drained by polls), so
        # /metrics shows lifetime totals while grab_stats keeps its
        # delta semantics.  Outside the fuzzer lock — the counter has
        # its own, and ordering between the two surfaces is free.
        _STAT_COUNTERS[s].inc(v)

    def exec_count(self) -> int:
        """Monotonic total executions (not drained by grab_stats)."""
        with self._lock:
            return self._exec_total

    def grab_stats(self) -> dict[str, int]:
        """Drain counters for a manager poll (fuzzer.go:323-338).

        The snapshot AND the reset happen under one lock acquisition:
        proc threads inc() concurrently, and a read-then-separately-
        reset would lose every increment that lands between the two
        (test_telemetry.py pins the conservation invariant)."""
        with self._lock:
            grabbed, self.stats = self.stats, [0] * len(Stat)
        return {STAT_NAMES[Stat(i)]: v
                for i, v in enumerate(grabbed) if v}

    def restore_poll_data(self, sig: Signal, stats: dict[str, int]) -> None:
        """Re-queue drained poll payload after a failed RPC so the
        delta is retransmitted next time."""
        by_name = {name: s for s, name in STAT_NAMES.items()}
        with self._lock:
            self.new_signal.merge(sig)
            for name, v in stats.items():
                s = by_name.get(name)
                if s is not None:
                    self.stats[s] += v

    # -- signal bookkeeping ----------------------------------------------

    def set_triage(self, engine) -> None:
        """Install the device-plane triage engine as the novelty
        pre-filter (seeded from the current max_signal); from here on
        check_new_signal_fn routes through it and max-signal merges
        scatter into its plane."""
        engine.attach(self)
        self.triage = engine

    def check_new_signal(self, p: Prog, infos, source=None,
                         proc=None) -> list[tuple[int, Signal]]:
        """Per-call novelty test against max_signal; returns calls with
        new signal and updates max/new signal under one lock
        (reference: fuzzer.go:494-511)."""
        return self.check_new_signal_fn(
            lambda errno, idx: signal_prio(p, errno, idx), infos,
            source=source, proc=proc)

    def check_new_signal_fn(self, prio_fn, infos, trace=None,
                            source=None,
                            proc=None) -> list[tuple[int, Signal]]:
        """check_new_signal with a caller-supplied prio_fn(errno,
        call_index) — lets undecoded device mutants compute edge
        priority from their exec-template flags without a typed
        decode (ops/pipeline.ExecMutant.signal_prio).

        With a TriageEngine installed, the batched device plane
        pre-filters: only calls flagged possibly-novel reach the
        exact per-call dict diff below — the common nothing-new case
        never takes the lock (syzkaller_tpu/triage).

        `trace` is the executed mutant's lineage context: verdict
        delivery is a hop on its correlated track
        (telemetry/lineage.py).  `source`/`proc` are the executed
        program's workqueue lane and worker id: confirmed novel edges
        are attributed to them (telemetry/coverage.py —
        `tz_coverage_novel_edges_total{source=...}` + the per-proc
        rollup), and the no-news case ticks the plateau detector."""
        eng = self.triage
        if eng is not None:
            news = eng.check(self, prio_fn, infos, trace=trace,
                             source=source)
        else:
            news = self.cpu_check_new_signal(prio_fn, infos)
            lineage.hop(trace, "triage.verdict")
        if news:
            telemetry.COVERAGE.note_novel(
                source, sum(len(d) for _ci, d in news), proc=proc)
        else:
            telemetry.COVERAGE.tick()
        return news

    def cpu_check_new_signal(self, prio_fn,
                             infos) -> list[tuple[int, Signal]]:
        """The exact CPU novelty check (the reference's shape, and the
        triage engine's confirm/fallback path): per-call Signal diffs
        and max/new-signal merges under one lock acquisition."""
        out = []
        with self._lock:
            for info in infos:
                prio = prio_fn(info.errno, info.call_index)
                diff = self.max_signal.diff_raw(info.signal, prio)
                if diff.empty():
                    continue
                self.max_signal.merge(diff)
                self.new_signal.merge(diff)
                out.append((info.call_index, diff))
        return out

    def corpus_signal_diff(self, sig: Signal) -> Signal:
        with self._lock:
            return self.corpus_signal.diff(sig)

    def grab_new_signal(self) -> Signal:
        """Drain the unreported delta (fuzzer.go:468-480)."""
        with self._lock:
            sig, self.new_signal = self.new_signal, Signal()
        return sig

    def add_max_signal(self, sig: Signal) -> None:
        """Merge manager-distributed max signal (fuzzer.go:482-486).
        The triage plane absorbs the same merge (after the max_signal
        merge, so the plane never gets ahead of the exact sets)."""
        with self._lock:
            self.max_signal.merge(sig)
        if self.triage is not None:
            self.triage.merge_signal(sig)

    # -- corpus ----------------------------------------------------------

    def add_input_to_corpus(self, p: Prog, sig: Signal, cover: Cover,
                            serialized: Optional[bytes] = None) -> Optional[CorpusItem]:
        data = serialized if serialized is not None else serialize_prog(p)
        key = hash_string(data)
        with self._lock:
            if key in self.corpus_hashes:
                return None
            item = CorpusItem(p=p, serialized=data, sig=key, signal=sig,
                              cover=cover)
            self.corpus.append(item)
            self.corpus_hashes.add(key)
            self.corpus_signal.merge(sig)
        return item

    def corpus_len(self) -> int:
        with self._lock:
            return len(self.corpus)

    def corpus_snapshot(self) -> list[CorpusItem]:
        with self._lock:
            return list(self.corpus)

    def choose_corpus_prog(self, rng) -> Optional[Prog]:
        with self._lock:
            if not self.corpus:
                return None
            return self.corpus[rng.intn(len(self.corpus))].p

    # -- manager integration ---------------------------------------------

    def send_input_to_manager(self, item: CorpusItem, call_index: int,
                              trace=None) -> None:
        """Report a triaged input (fuzzer.go:423-440); no-op
        standalone.  `trace` rides the RPC frame header so the
        manager-side receive joins the mutant's lineage track."""
        if self.conn is None:
            return
        elems, prios = item.signal.serialize()
        # Session-tagged when the transport supports it: the manager's
        # reply cache then makes a retried send at-most-once.  Test
        # doubles without call_session get the plain path.
        call = getattr(self.conn, "call_session", None) or self.conn.call
        call("Manager.NewInput", {
            "name": getattr(self.conn, "name", "fuzzer"),
            "call_index": call_index,
            "input": {
                "call": item.p.calls[call_index].meta.name,
                "prog": item.serialized.decode(),
                "signal": [elems, prios],
                "cover": item.cover.serialize(),
            },
        }, trace=trace)

    def record_crash(self, console_log: str, last_prog: Optional[Prog]) -> None:
        self.stat_add(Stat.CRASHES)
        log.logf(0, "kernel crash detected (%d bytes of console log)",
                 len(console_log))
        if self.conn is not None and console_log:
            # Under a manager the instance console is the crash
            # channel (reference: the guest kernel prints the oops to
            # the serial console that MonitorExecution scans).  Our
            # "kernel console" is the executor's captured stderr —
            # replay it so the monitor sees the oops and the manager
            # saves/repros the crash.
            import sys as _sys

            _sys.stderr.write(console_log if console_log.endswith("\n")
                              else console_log + "\n")
            _sys.stderr.flush()
        if self.on_crash is not None:
            self.on_crash(console_log, last_prog)
