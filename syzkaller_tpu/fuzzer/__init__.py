"""Guest-side fuzzing driver (reference: syz-fuzzer/).

The Fuzzer owns shared state (corpus, signal sets, choice table), Procs
run the per-worker loop against executor Envs, and the WorkQueue
prioritizes triage/candidate/smash work items.  The TPU twist: procs
can draw exec-ready mutants from a shared PipelineMutator draining the
device-resident corpus pipeline instead of mutating one program at a
time.
"""

from syzkaller_tpu.fuzzer.workqueue import (
    WorkQueue,
    WorkTriage,
    WorkCandidate,
    WorkSmash,
)
from syzkaller_tpu.fuzzer.fuzzer import Fuzzer, FuzzerConfig, signal_prio
from syzkaller_tpu.fuzzer.proc import Proc

__all__ = [
    "WorkQueue", "WorkTriage", "WorkCandidate", "WorkSmash",
    "Fuzzer", "FuzzerConfig", "signal_prio", "Proc",
]
