"""Host feature/syscall support detection (reference: pkg/host/).

The reference probes the live kernel (test syscalls, /proc and /dev
paths, KCOV/fault-injection sysfs knobs — pkg/host/host_linux.go:20-216).
Here the "host" is the executor's backend: the simulated kernel
supports every described call, while a real-OS backend restricts by
syscall-number presence and probe hooks registered per target.
"""

from __future__ import annotations

from typing import Callable, Optional

from syzkaller_tpu.models.target import Target

# Per-(os) probe hooks: name -> fn(syscall, sandbox) -> reason-or-None.
_probes: dict[str, Callable] = {}


def register_probe(os: str, fn: Callable) -> None:
    _probes[os] = fn


def detect_supported_syscalls(target: Target, sandbox: str = "none",
                              enabled: Optional[set[int]] = None
                              ) -> tuple[list, dict]:
    """Returns (supported syscalls, {syscall: reason} for unsupported)
    (reference: pkg/host/host.go:12-40)."""
    supported = []
    unsupported = {}
    probe = _probes.get(target.os)
    for c in target.syscalls:
        if enabled is not None and c.id not in enabled:
            continue
        if c.nr < 0:
            unsupported[c] = "no syscall number"
            continue
        if probe is not None:
            reason = probe(c, sandbox)
            if reason is not None:
                unsupported[c] = reason
                continue
        supported.append(c)
    return supported, unsupported


def check_fault_injection() -> bool:
    """Whether the backend supports fail-nth fault injection.  The sim
    kernel always does (executor/sim_kernel.h fault arm); a real-linux
    backend would stat /sys/kernel/debug/failslab
    (reference: pkg/host/host_linux.go:216-240)."""
    return True


def enabled_calls(target: Target, supported: list,
                  sandbox: str = "none") -> tuple[dict, dict]:
    """Transitive closure over resource constructors: a call is enabled
    only if every input resource is transitively creatable
    (reference: syz-fuzzer/fuzzer.go:384-421 + prog/resources.go:88)."""
    enabled_map = {c: True for c in supported}
    enabled, disabled = target.transitively_enabled_calls(enabled_map)
    return enabled, disabled
