"""Host feature/syscall support detection (reference: pkg/host/).

The reference probes the live kernel: issue each syscall with
all-invalid arguments and treat ENOSYS as "not implemented", check
the filesystem paths that file-opening calls reference, and stat the
debugfs knobs behind coverage/fault-injection
(reference: pkg/host/host_linux.go:20-240).  The sim backend supports
every described call; the linux backend uses the real probes below.
"""

from __future__ import annotations

import errno
import functools
import os
from typing import Callable, Optional

from syzkaller_tpu.models.target import Target

# Per-(os) probe hooks: name -> fn(syscall, sandbox) -> reason-or-None.
_probes: dict[str, Callable] = {}


def register_probe(os_name: str, fn: Callable) -> None:
    _probes[os_name] = fn


def detect_supported_syscalls(target: Target, sandbox: str = "none",
                              enabled: Optional[set[int]] = None,
                              backend: str = "sim") -> tuple[list, dict]:
    """Returns (supported syscalls, {syscall: reason} for unsupported)
    (reference: pkg/host/host.go:12-40).

    Support is a property of the EXECUTION BACKEND, not of the machine
    the fuzzer process runs on: the sim backend implements every
    described call, so the kernel probes only run for backend="linux"
    (where programs hit the host kernel for real)."""
    supported = []
    unsupported = {}
    probe = _probes.get(target.os) if backend == "linux" else None
    for c in target.syscalls:
        if enabled is not None and c.id not in enabled:
            continue
        if c.nr < 0:
            unsupported[c] = "no syscall number"
            continue
        if probe is not None:
            reason = probe(c, sandbox)
            if reason is not None:
                unsupported[c] = reason
                continue
        supported.append(c)
    return supported, unsupported


def check_fault_injection(backend: str = "sim") -> bool:
    """Whether the backend supports fail-nth fault injection.  The sim
    kernel always does (executor/sim_kernel.h fault arm); real linux
    needs CONFIG_FAULT_INJECTION's debugfs knobs
    (reference: pkg/host/host_linux.go:216-240)."""
    if backend != "linux":
        return True
    return os.path.exists("/sys/kernel/debug/failslab") or \
        os.path.exists("/proc/self/make-it-fail")


def check_coverage(backend: str = "sim") -> bool:
    """KCOV availability (reference: host_linux.go checkCoverage).
    The sim backend computes coverage in-process — always on."""
    if backend != "linux":
        return True
    return os.path.exists("/sys/kernel/debug/kcov")


def check_comparisons(backend: str = "sim") -> bool:
    """KCOV_TRACE_CMP needs KCOV plus a recent-enough kernel; presence
    of the kcov node is the host-side gate (the executor degrades at
    ioctl time if CMP tracing is absent)."""
    return check_coverage(backend)


def enabled_calls(target: Target, supported: list,
                  sandbox: str = "none") -> tuple[dict, dict]:
    """Transitive closure over resource constructors: a call is enabled
    only if every input resource is transitively creatable
    (reference: syz-fuzzer/fuzzer.go:384-421 + prog/resources.go:88)."""
    enabled_map = {c: True for c in supported}
    enabled, disabled = target.transitively_enabled_calls(enabled_map)
    return enabled, disabled


# ---- the linux probe -------------------------------------------------

from syzkaller_tpu.ipc.env import PSEUDO_NR_BASE  # noqa: E402  (single source)

# Pseudo-syscalls gate on the kernel facility they wrap
# (executor/pseudo_linux.h dispatch).
_PSEUDO_REQUIRES = {
    "syz_emit_ethernet": "/dev/net/tun",
    "syz_extract_tcp_res": "/dev/net/tun",
    "syz_kvm_setup_cpu": "/dev/kvm",
    "syz_mount_image": "/dev/loop-control",
    "syz_read_part_table": "/dev/loop-control",
    "syz_open_pts": "/dev/ptmx",
}

# Devices whose mere OPEN arms machine-level state: /dev/watchdog
# starts the watchdog timer, and a close without the magic 'V' write
# leaves it running — the VM hard-reboots after the timeout and the
# manager records a spurious lost-connection crash.  Described for
# completeness (operators can enable explicitly), disabled by default
# (the reference takes the same dangerous-device stance in its
# sanitize layer).
_DANGEROUS_PATHS = {
    "/dev/watchdog": "arms the watchdog timer (would reboot the VM)",
    "/dev/watchdog0": "arms the watchdog timer (would reboot the VM)",
}

# Never issue these as probes: they block, signal, fork, kill the
# process, or flip process-wide state even with bogus arguments
# (reference keeps the same kind of special-case list,
# host_linux.go isSupportedSyscall).  All are baseline linux calls;
# treat as present.
_NO_PROBE = frozenset("""
exit exit_group rt_sigreturn pause kill tkill tgkill fork vfork clone
clone3 execve execveat reboot vhangup umask personality setsid setpgid
setuid setgid setreuid setregid setresuid setresgid setfsuid setfsgid
setgroups capset chroot pivot_root sync syncfs munlockall mlockall
shutdown close_range rt_sigsuspend sigsuspend wait4 waitid waitpid
ptrace seccomp unshare setns iopl ioperm futex
""".split())
# futex: the kernel answers ENOSYS for an invalid futex OP, so the
# all-invalid-args probe would falsely mark it unimplemented.


@functools.lru_cache(maxsize=1)
def _libc():
    import ctypes

    return ctypes.CDLL(None, use_errno=True)


@functools.lru_cache(maxsize=None)
def _nr_implemented(nr: int) -> bool:
    """ENOSYS probe: issue the syscall with all-invalid args; any
    other outcome (EFAULT/EBADF/EINVAL/...) proves the entry point
    exists (reference: host_linux.go:20-60)."""
    import ctypes

    libc = _libc()
    bad = ctypes.c_long(-1)
    res = libc.syscall(ctypes.c_long(nr), bad, bad, bad, bad, bad, bad)
    if res != -1:
        return True
    return ctypes.get_errno() != errno.ENOSYS


def _const_path_arg(c) -> Optional[str]:
    """The fixed filename a call opens, when statically known (string
    type with exactly one value among its pointer args)."""
    from syzkaller_tpu.models.types import BufferKind, BufferType, PtrType

    for a in c.args:
        if isinstance(a, PtrType) and isinstance(a.elem, BufferType) \
                and a.elem.kind == BufferKind.STRING \
                and len(a.elem.values) == 1:
            v = a.elem.values[0].rstrip(b"\x00")
            if v.startswith(b"/"):
                return v.decode("utf-8", "replace")
    return None


def _linux_probe(c, sandbox: str) -> Optional[str]:
    if c.nr >= PSEUDO_NR_BASE:
        need = _PSEUDO_REQUIRES.get(c.call_name)
        if need is not None and not os.path.exists(need):
            return f"{need} is absent"
        if c.call_name == "syz_open_dev":
            # variants with a fixed device template: the device must
            # exist (reference: isSupportedSyzOpenDev)
            path = _const_path_arg(c)
            if path is not None and not os.path.exists(
                    path.replace("#", "0")):
                return f"{path} does not exist"
        return None
    # file-opening variants with a fixed path: the path must exist
    # (reference: isSupportedOpenAt)
    if c.call_name in ("open", "openat", "creat"):
        path = _const_path_arg(c)
        if path is not None:
            if path in _DANGEROUS_PATHS:
                return _DANGEROUS_PATHS[path]
            probe = path.replace("#", "0")
            if not os.path.exists(probe):
                return f"{probe} does not exist"
        return None
    if c.call_name in _NO_PROBE:
        return None
    if not _nr_implemented(c.nr):
        return "syscall is not implemented (ENOSYS)"
    return None


def _maybe_register_linux() -> None:
    # the probe issues real syscalls: only meaningful on a linux host
    if os.path.exists("/proc/version"):
        register_probe("linux", _linux_probe)


_maybe_register_linux()
