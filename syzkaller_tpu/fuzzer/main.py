"""Guest-side fuzzer process: connect → check → fuzz + poll loop.

The syz-fuzzer form factor (reference: syz-fuzzer/fuzzer.go:97-382):
connects to the manager, downloads prios/corpus/candidates, builds the
choice table, spawns N proc loops, and syncs stats/maxSignal/
candidates with the manager on a poll cadence.  Also runnable
standalone (no manager) as the syz-stress form.
"""

from __future__ import annotations

import argparse
import os
import threading
import time
from typing import Optional

from syzkaller_tpu import telemetry
from syzkaller_tpu.fuzzer.fuzzer import Fuzzer, FuzzerConfig
from syzkaller_tpu.fuzzer.host import (check_fault_injection,
                                       detect_supported_syscalls,
                                       enabled_calls)
from syzkaller_tpu.fuzzer.proc import Proc
from syzkaller_tpu.fuzzer.workqueue import (ProgTypes, WorkCandidate,
                                            WorkQueue)
from syzkaller_tpu.ipc.env import make_env
from syzkaller_tpu.models.encoding import ParseError, deserialize_prog
from syzkaller_tpu.models.prio import build_choice_table
from syzkaller_tpu.models.target import get_target
from syzkaller_tpu.rpc import RPCClient
from syzkaller_tpu.signal import Signal
from syzkaller_tpu.utils import log

POLL_PERIOD_S = 10.0  # reference: fuzzer.go:300-382 poll cadence


def _telemetry_payload() -> dict:
    """The fuzzer's registry snapshot, trimmed for the poll wire:
    counters/gauges/histograms only (events are per-process operator
    timelines; the manager merge has no use for them)."""
    snap = telemetry.snapshot()
    return {"counters": snap["counters"], "gauges": snap["gauges"],
            "histograms": snap["histograms"]}


class FuzzerProcess:
    """Wires Fuzzer + N Procs + the manager poll loop."""

    def __init__(self, name: str, target_name: tuple[str, str],
                 manager_addr: Optional[tuple[str, int]] = None,
                 procs: int = 1, sim: bool = True,
                 cfg: Optional[FuzzerConfig] = None,
                 engine: str = "cpu"):
        self.name = name
        self.target = get_target(*target_name)
        self.procs_n = procs
        self.sim = sim
        self.stop = threading.Event()
        self.conn = RPCClient(manager_addr, name=name) \
            if manager_addr else None

        backend = "sim" if sim else self.target.os
        supported, _unsup = detect_supported_syscalls(self.target,
                                                      backend=backend)
        enabled, disabled = enabled_calls(self.target, supported)
        self.enabled = sorted(c.id for c in enabled)
        for c, reason in disabled.items():
            log.logf(1, "disabled %s: %s", c.name, reason)

        self.backend = backend
        self.poll_period_s = POLL_PERIOD_S
        connect_res = {}
        if self.conn is not None:
            connect_res = self._connect()

        ct_calls = {c: True for c in self.target.syscalls
                    if c.id in set(self.enabled)}
        self.fuzzer = Fuzzer(
            self.target, WorkQueue(), cfg=cfg,
            ct=build_choice_table(self.target, enabled=ct_calls),
            conn=self.conn)

        # Seed from the manager's corpus + candidates
        # (reference: fuzzer.go:167-229).
        for inp in connect_res.get("corpus") or []:
            self._add_corpus_input(inp)
        ms = connect_res.get("max_signal") or [[], []]
        self.fuzzer.add_max_signal(Signal.deserialize(ms[0], ms[1]))
        for cand in connect_res.get("candidates") or []:
            self._enqueue_candidate(cand)

        self.mutator = None
        self.hint_lane = None
        if engine == "jax":
            # TZ_JAX_PLATFORM lets a supervisor (e.g. the demo) pin
            # fuzzer subprocesses to a working backend instead of a
            # wedged tunnel (see utils/jaxenv.py for why env vars
            # alone do not work).
            from syzkaller_tpu.utils.jaxenv import (
                enable_compilation_cache, pin_jax_platform)

            pin_jax_platform()
            # Fuzzer restarts must not re-pay the ~2min tunnel compile
            # of the pipeline step.
            enable_compilation_cache()
            from syzkaller_tpu.fuzzer.proc import PipelineMutator
            from syzkaller_tpu.ops.pipeline import DevicePipeline

            # Share the enabled-filtered choice table so the donor
            # bank cannot splice manager-disabled syscalls.
            self.mutator = PipelineMutator(
                DevicePipeline(self.target, ct=self.fuzzer.ct))
            # Device-plane novelty triage co-resident with the corpus
            # ring (syzkaller_tpu/triage): shares the pipeline's
            # breaker/watchdog, demotes to the CPU path with it.
            # TZ_TRIAGE_DEVICE=0 is the kill switch back to the
            # per-call CPU Signal diffs.
            from syzkaller_tpu.health import env_int

            if env_int("TZ_TRIAGE_DEVICE", 1):
                from syzkaller_tpu.triage import TriageEngine

                self.fuzzer.set_triage(
                    TriageEngine.for_pipeline(self.mutator.pipeline))
            # Fleet-wide batched hints lane (ops/hintlane): all procs
            # stage comparison windows into one fused device batch
            # under the flush-leader discipline; shares the pipeline's
            # breaker so a sick device demotes hints with it.
            # TZ_HINTS_LANE=0 falls back to the per-program device
            # path (mutate_with_hints_device).
            if env_int("TZ_HINTS_LANE", 1):
                from syzkaller_tpu.ops.hintlane import HintLane

                self.hint_lane = HintLane.for_pipeline(
                    self.mutator.pipeline)

        self.procs = []
        for pid in range(procs):
            env = make_env(pid, sim=sim)
            self.procs.append(Proc(self.fuzzer, pid, env,
                                   mutator=self.mutator,
                                   device_hints=engine == "jax",
                                   hint_lane=self.hint_lane))

    # -- manager session ---------------------------------------------------

    def _connect(self) -> dict:
        """Manager.Connect + the capability check, arming the
        idempotency session from the minted epoch (docs/health.md).
        The installed on_reconnect hook makes every later
        call_session self-healing across manager restarts."""
        res = self.conn.call("Manager.Connect", {"name": self.name}) \
            or {}
        if res.get("epoch"):
            self.conn.set_session(res["epoch"],
                                  on_reconnect=self._resync)
        if res.get("need_check"):
            from syzkaller_tpu.fuzzer.host import (check_comparisons,
                                                   check_coverage)

            self.conn.call("Manager.Check", {
                "name": self.name,
                "kcov": check_coverage(self.backend),
                "comps": check_comparisons(self.backend),
                "fault": check_fault_injection(self.backend),
                "leak": False, "calls": self.enabled,
            })
        return res

    def _resync(self) -> None:
        """Full re-Connect resync after ReconnectRequired: the manager
        restarted or reaped our lease, so its reply carries the whole
        corpus + max signal again.  Re-ingesting is idempotent — the
        corpus dedups by program hash, signal merges are monotonic —
        and the interrupted call is then re-issued under the fresh
        epoch by call_session."""
        log.logf(0, "manager session lost; reconnecting + resyncing")
        res = self._connect()
        for inp in res.get("corpus") or []:
            self._add_corpus_input(inp)
        ms = res.get("max_signal") or [[], []]
        self.fuzzer.add_max_signal(Signal.deserialize(ms[0], ms[1]))
        for cand in res.get("candidates") or []:
            self._enqueue_candidate(cand)

    def _device_state(self) -> str:
        """This fuzzer's device health for the manager's admission
        controller: the pipeline breaker's state, "closed" on the CPU
        engine (no breaker, nothing to throttle for)."""
        if self.mutator is None:
            return "closed"
        br = getattr(self.mutator.pipeline, "breaker", None)
        return br.state if br is not None else "closed"

    # -- corpus/candidate intake -----------------------------------------

    def _add_corpus_input(self, inp: dict) -> None:
        try:
            p = deserialize_prog(self.target, inp["prog"].encode())
        except (ParseError, KeyError) as e:
            log.logf(1, "rejecting corpus input: %s", e)
            return
        sig = Signal.deserialize(*(inp.get("signal") or [[], []]))
        from syzkaller_tpu.signal.cover import Cover

        cover = Cover(inp.get("cover") or [])
        self.fuzzer.add_input_to_corpus(p, sig, cover)

    def _enqueue_candidate(self, cand: dict) -> None:
        try:
            p = deserialize_prog(self.target, cand["prog"].encode())
        except (ParseError, KeyError) as e:
            log.logf(1, "rejecting candidate: %s", e)
            return
        self.fuzzer.wq.enqueue(WorkCandidate(
            p=p, flags=ProgTypes(minimized=bool(cand.get("minimized")),
                                 smashed=bool(cand.get("smashed")))))

    # -- loops ------------------------------------------------------------

    def run(self, duration_s: Optional[float] = None,
            iterations: int = 1 << 62) -> None:
        threads = []
        for proc in self.procs:
            t = threading.Thread(target=proc.loop,
                                 args=(iterations,), kwargs={"stop": self.stop},
                                 daemon=True)
            t.start()
            threads.append(t)
        poller = threading.Thread(target=self.poll_loop, daemon=True)
        poller.start()
        deadline = time.monotonic() + duration_s if duration_s else None
        try:
            for t in threads:
                while t.is_alive():
                    t.join(timeout=0.5)
                    if deadline and time.monotonic() > deadline:
                        self.stop.set()
        finally:
            self.stop.set()
            if self.mutator is not None:
                # Wake procs blocked in pipeline.next() before joining.
                self.mutator.pipeline.stop()
            for t in threads:
                t.join(timeout=5)
            self.shutdown()

    def poll_loop(self) -> None:
        """(reference: fuzzer.go:300-382)"""
        execs_reported = 0
        while not self.stop.is_set():
            # The wait honours the manager's throttle hint: a degraded
            # chip stretches the cadence (admission control).
            self.stop.wait(self.poll_period_s)
            if self.stop.is_set():
                return
            # Keep-alive print doubles as the liveness marker scanned
            # by monitor_execution (fuzzer.go:312-315) — only emitted
            # when executions actually progressed, so a wedged fuzzer
            # trips the not-executing watchdog.
            execs = self.fuzzer.exec_count()
            if execs != execs_reported:
                execs_reported = execs
                log.logf(0, "alive, executing program (%d total)", execs)
            if self.conn is None:
                continue
            try:
                self.poll_once()
            except Exception as e:
                log.logf(0, "poll failed: %s", e)

    def poll_once(self, need_candidates: Optional[bool] = None) -> dict:
        new_sig = self.fuzzer.grab_new_signal()
        stats = self.fuzzer.grab_stats()
        if need_candidates is None:
            need_candidates = self.fuzzer.wq.want_candidates()
        try:
            # call_session retries across connection faults (the
            # server's reply cache makes the resend idempotent) and
            # resyncs through _resync on a manager restart.
            res = self.conn.call_session("Manager.Poll", {
                "name": self.name,
                "need_candidates": bool(need_candidates),
                "stats": stats,
                "max_signal": list(new_sig.serialize()),
                "device_state": self._device_state(),
                # Cumulative registry snapshot for the manager's
                # cross-process histogram merge (fixed shared buckets;
                # latest-wins per fuzzer, so unlike the drained stats
                # above it needs no restore on a failed RPC).
                "telemetry": _telemetry_payload(),
            }) or {}
        except Exception:
            # The drained delta must not be lost when even the retry
            # path gives up — put it back for the next poll.  (A retry
            # that succeeded via the reply cache needs no restore: the
            # delta was applied exactly once server-side.)
            self.fuzzer.restore_poll_data(new_sig, stats)
            raise
        ms = res.get("max_signal") or [[], []]
        self.fuzzer.add_max_signal(Signal.deserialize(ms[0], ms[1]))
        for inp in res.get("new_inputs") or []:
            self._add_corpus_input(inp)
        for cand in res.get("candidates") or []:
            self._enqueue_candidate(cand)
        th = res.get("throttle") or {}
        mult = max(1.0, float(th.get("poll_interval_mult") or 1.0))
        period = min(POLL_PERIOD_S * mult, 120.0)
        if period != self.poll_period_s:
            log.logf(0, "manager throttle hint: state=%s, poll period "
                     "%.0fs", th.get("state", "closed"), period)
            self.poll_period_s = period
        return res

    def shutdown(self) -> None:
        if self.mutator is not None:
            self.mutator.pipeline.stop()  # no-op if already stopped
        for proc in self.procs:
            try:
                proc.env.close()
            except Exception:
                pass


def main(argv: Optional[list[str]] = None) -> None:
    ap = argparse.ArgumentParser(prog="tz-fuzzer")
    ap.add_argument("-name", default="fuzzer")
    ap.add_argument("-manager", default="",
                    help="manager RPC addr host:port")
    ap.add_argument("-os", dest="target_os", default="test")
    ap.add_argument("-arch", default="64")
    ap.add_argument("-procs", type=int, default=1)
    ap.add_argument("-engine", default="cpu", choices=["cpu", "jax"])
    ap.add_argument("-duration", type=float, default=0,
                    help="seconds to run (0 = forever)")
    ap.add_argument("-v", type=int, default=0)
    args = ap.parse_args(argv)
    log.set_level(args.v)
    addr = None
    if args.manager:
        from syzkaller_tpu.manager.mgrconfig import parse_addr

        addr = parse_addr(args.manager)
    # Flight recorder (telemetry/flight.py): a production fuzzer dumps
    # incident files on DeviceWedged / breaker-open / SIGTERM.  The
    # dump dir defaults to the working directory unless TZ_FLIGHT_DIR
    # already armed it; library/test use stays disarmed.
    if not telemetry.FLIGHT.armed():
        telemetry.FLIGHT.set_dir(os.getcwd())
    from syzkaller_tpu.telemetry import flight as _flight

    _flight.install_signal_handler()
    fp = FuzzerProcess(args.name, (args.target_os, args.arch),
                       manager_addr=addr, procs=args.procs,
                       engine=args.engine)
    fp.run(duration_s=args.duration or None)


if __name__ == "__main__":
    main()
