"""The TPU mutation engine: host orchestration of the device hot loop.

Sits behind the Target API as the optional batched mutation engine the
north star describes: corpus programs are encoded once into program
tensors, mutated in large batches on the TPU, decoded back to typed
programs and serialized for the (unchanged) executors.  Structural
ops the device cannot express — call insertion (51% of reference
mutation iterations), ANY-squash, corpus splice — run on the host for
the slice of programs whose op class demands them, so the end-to-end
op distribution stays faithful to the reference's weighted loop
(reference: prog/mutation.go:19-131; host/TPU split per SURVEY.md §7).
"""

from __future__ import annotations

import random as py_random
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from syzkaller_tpu.models.prog import Prog
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.mutation import mutate_prog
from syzkaller_tpu.ops.tensor import (
    FlagTables,
    ProgTensor,
    TensorConfig,
    decode_prog,
    encode_prog,
    stack_batch,
)

# Reference per-iteration op probabilities (prog/mutation.go:19-131):
# squash 1/5; then splice 1/100; then insert 20/31; then arg-mutate
# 10/11 of the rest; else remove.  Device ops cover {arg-mutate,
# remove}; {squash, splice, insert} are host structural ops.
P_HOST_STRUCTURAL = 0.2 + 0.8 * (1 / 100) + 0.8 * (99 / 100) * (20 / 31)


@dataclass
class EngineStats:
    device_mutations: int = 0
    host_mutations: int = 0
    decode_failures: int = 0


class TpuEngine:
    """Batched mutation engine over a device mesh."""

    def __init__(self, target, cfg: Optional[TensorConfig] = None,
                 rounds: int = 4, seed: int = 0,
                 host_fraction: float = P_HOST_STRUCTURAL):
        import jax
        import jax.numpy as jnp
        from jax import random as jrandom

        from syzkaller_tpu.ops.mutate import make_mutator

        self.jnp = jnp
        self.jrandom = jrandom
        self.target = target
        self.cfg = cfg or TensorConfig()
        self.flags = FlagTables.empty()
        self.mutate_batch = make_mutator(rounds)
        self.key = jrandom.key(seed)
        self.host_rng = RandGen(target, seed ^ 0x5EED)
        self.py_rng = py_random.Random(seed)
        self.host_fraction = host_fraction
        self.stats = EngineStats()

    # -- corpus management ----------------------------------------------

    def encode(self, p: Prog) -> Optional[ProgTensor]:
        try:
            return encode_prog(p, self.cfg, self.flags)
        except Exception:
            return None

    # -- mutation --------------------------------------------------------

    def mutate(self, templates: list[ProgTensor], ct=None,
               corpus: Optional[list[Prog]] = None) -> list[Prog]:
        """Produce one mutant per template.  A host-sampled fraction
        goes through the CPU structural mutator; the rest through the
        batched device kernel."""
        jnp, jrandom = self.jnp, self.jrandom
        corpus = corpus or []
        host_idx = [i for i in range(len(templates))
                    if self.py_rng.random() < self.host_fraction]
        host_set = set(host_idx)
        out: list[Optional[Prog]] = [None] * len(templates)

        dev_idx = [i for i in range(len(templates)) if i not in host_set]
        if dev_idx:
            batch = stack_batch([templates[i] for i in dev_idx])
            self.key, sub = jrandom.split(self.key)
            mutated = self.mutate_batch(
                {k: jnp.asarray(v) for k, v in batch.items()}, sub,
                jnp.asarray(self.flags.vals), jnp.asarray(self.flags.counts))
            mutated_np = {k: np.asarray(v) for k, v in mutated.items()}
            for j, i in enumerate(dev_idx):
                mut = {k: v[j] for k, v in mutated_np.items()}
                try:
                    out[i] = decode_prog(
                        templates[i], mut,
                        preserve_sizes=bool(mut["preserve_sizes"]))
                    self.stats.device_mutations += 1
                except Exception:
                    self.stats.decode_failures += 1
                    out[i] = templates[i].template.clone()

        for i in host_idx:
            p = templates[i].template.clone()
            mutate_prog(p, self.host_rng, ncalls=self.cfg.max_calls - 2,
                        ct=ct, corpus=corpus)
            self.stats.host_mutations += 1
            out[i] = p
        return out  # type: ignore[return-value]
