"""syzkaller-tpu: a TPU-native coverage-guided kernel-fuzzing framework.

The framework has the capabilities of syzkaller (reference at
/root/reference): an unsupervised, coverage-guided OS-kernel fuzzer.
Unlike the reference (Go + C++ executor, one-program-at-a-time
mutation), the fuzzing hot loop here — program mutation, random
generation distributions, comparison-hint mutation and coverage-signal
triage — is built batch-first on JAX/XLA/Pallas: thousands of
flattened syscall programs are mutated and triaged in parallel on a
TPU mesh, with corpus novelty computed against a sharded coverage
bitmap by a single collective.

Package layout:
  models/    program model: type system, args, calls, progs, targets,
             generation/mutation semantics, serialization (the CPU
             reference plane; mirrors reference prog/)
  ops/       batched JAX/Pallas kernels: program-tensor mutation,
             RNG distributions, signal bitmaps, hints
  parallel/  device-mesh sharding, collectives, multi-host design
  sys/       syscall description models (test OS, linux subset)
  compiler/  syzlang description compiler (reference pkg/ast+compiler)
  signal/    feedback-signal model (reference pkg/signal, pkg/cover)
  ipc/       executor IPC: exec-format shuttle to executors
  fuzzer/    guest-side fuzz loop: workqueue, triage, smash
  manager/   host-side orchestration: corpus, RPC, VM loop
  vm/        VM pool abstraction
  report/    crash report parsing and symbolization
  repro/     automatic reproducer extraction
  utils/     rng, db, config, logging, hashing
"""

__version__ = "0.1.0"
