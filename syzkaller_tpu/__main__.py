"""Unified CLI dispatcher: `python -m syzkaller_tpu <tool> [args...]`.

Mirrors the reference's bin/syz-* binaries (Makefile:3-28 build
matrix) as subcommands of one entry point.
"""

from __future__ import annotations

import sys

_TOOLS = {
    "manager": ("syzkaller_tpu.tools.manager_tool", "the manager daemon"),
    "fuzzer": ("syzkaller_tpu.fuzzer.main", "guest-side fuzzer process"),
    "hub": ("syzkaller_tpu.hub.hub", "corpus-exchange hub server"),
    "execprog": ("syzkaller_tpu.tools.execprog", "execute programs"),
    "stress": ("syzkaller_tpu.tools.stress", "local stress fuzzing"),
    "mutate": ("syzkaller_tpu.tools.mutate", "mutate a single program"),
    "prog2c": ("syzkaller_tpu.tools.prog2c", "program → C translator"),
    "repro": ("syzkaller_tpu.tools.repro_tool",
              "extract reproducer from crash log"),
    "crush": ("syzkaller_tpu.tools.crush", "replay crash log"),
    "db": ("syzkaller_tpu.tools.db_tool", "corpus.db pack/unpack/merge"),
    "benchcmp": ("syzkaller_tpu.tools.benchcmp",
                 "render bench JSON to HTML charts"),
    "symbolize": ("syzkaller_tpu.tools.symbolize",
                  "symbolize a crash report"),
    "fmt": ("syzkaller_tpu.tools.fmt", "format syzlang descriptions"),
    "upgrade": ("syzkaller_tpu.tools.upgrade",
                "migrate a corpus.db to the current format"),
    "demo": ("syzkaller_tpu.tools.demo",
             "one-command full-stack demo (manager+VMs+fuzzer+repro)"),
    "tty": ("syzkaller_tpu.tools.tty",
            "console/serial reader with crash highlighting"),
    "imagegen": ("syzkaller_tpu.tools.imagegen",
                 "generate a VM disk-image build script"),
    "parse": ("syzkaller_tpu.tools.parse_tool",
              "extract programs from a fuzzer console log"),
    "headerparser": ("syzkaller_tpu.tools.headerparser",
                     "draft syzlang structs from C headers"),
}


def main() -> int:
    if len(sys.argv) < 2 or sys.argv[1] in ("-h", "--help", "help"):
        print("usage: python -m syzkaller_tpu <tool> [args...]\n\ntools:")
        for name, (_, desc) in sorted(_TOOLS.items()):
            print(f"  {name:<10} {desc}")
        return 0
    tool = sys.argv[1]
    entry = _TOOLS.get(tool)
    if entry is None:
        print(f"unknown tool {tool!r} (try: help)", file=sys.stderr)
        return 1
    import importlib

    mod = importlib.import_module(entry[0])
    ret = mod.main(sys.argv[2:])
    return ret if isinstance(ret, int) else 0


if __name__ == "__main__":
    sys.exit(main())
