"""Executor environment: process + shmem lifecycle and the exec loop.

Host side of the control protocol defined in executor/wire.h.  One Env
per proc (fork-server model): two mem-mapped files (2 MB program in,
16 MB results out — reference: pkg/ipc/ipc.go:54-55,195-214), pipes
for the control words, handshake carrying env flags + proc id, then
one ExecuteReq/ExecuteRep round per program (reference:
pkg/ipc/ipc.go:280-330,656-840).

The executor's stderr is captured to a rolling "console" file; when
the process dies mid-exec the accumulated stderr is surfaced as the
crash log (the moral equivalent of the VM console output scanned by
vm.MonitorExecution).
"""

from __future__ import annotations

import enum
import mmap
import os
import struct
import subprocess
import tempfile
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

import numpy as np

REPO_ROOT = Path(__file__).resolve().parents[2]
EXECUTOR_DIR = REPO_ROOT / "executor"
EXECUTOR_BIN = EXECUTOR_DIR / "tz-executor"

IN_SHMEM_SIZE = 2 << 20
OUT_SHMEM_SIZE = 16 << 20

HANDSHAKE_REQ_MAGIC = 0x745A6878616E6401
HANDSHAKE_REP_MAGIC = 0x745A6878616E6402
EXECUTE_REQ_MAGIC = 0x745A65786563710A
EXECUTE_REP_MAGIC = 0x745A65786563720B

STATUS_FAIL = 67
STATUS_ERROR = 68
STATUS_RETRY = 69

# syz_* pseudo-syscalls occupy a reserved NR range dispatched inside
# the executor (executor/wire.h kPseudoNrBase; values pinned in
# sys/descriptions/linux/pseudo_amd64.const — a test cross-checks all
# three stay in sync).
PSEUDO_NR_BASE = 0x81000000


class EnvFlags(enum.IntFlag):
    DEBUG = 1 << 0
    SIGNAL = 1 << 1
    SANDBOX_NONE = 1 << 2
    SANDBOX_SETUID = 1 << 3
    SANDBOX_NAMESPACE = 1 << 4
    SIM_OS = 1 << 5
    OPTIONAL_COVER = 1 << 6
    # Fork a fresh child per program (program exits/crashes are
    # contained; reference: common_linux.h:1931-2040).
    FORK_PROG = 1 << 7
    # Real-OS environment features (best-effort in the executor;
    # reference: common_linux.h:332 TUN, 1075 cgroups).
    ENABLE_TUN = 1 << 8
    ENABLE_CGROUPS = 1 << 9


class ExecFlags(enum.IntFlag):
    COLLECT_COVER = 1 << 0
    DEDUP_COVER = 1 << 1
    COLLECT_COMPS = 1 << 2
    THREADED = 1 << 3
    COLLIDE = 1 << 4
    FAULT = 1 << 5


class CallFlags(enum.IntFlag):
    EXECUTED = 1 << 0
    FINISHED = 1 << 1
    BLOCKED = 1 << 2
    FAULT_INJECTED = 1 << 3


@dataclass
class ExecOpts:
    flags: ExecFlags = ExecFlags(0)
    fault_call: int = -1
    fault_nth: int = 0


@dataclass
class CallInfo:
    call_index: int
    call_id: int
    errno: int
    flags: CallFlags
    signal: np.ndarray  # uint32
    cover: np.ndarray  # uint32
    comps: list[tuple[int, int]] = field(default_factory=list)


@dataclass
class ExecResult:
    info: list[CallInfo]
    completed: bool
    hanged: bool = False


class ExecutorFailure(Exception):
    """Executor-level failure (status 67/68): respawn and retry."""


class ExecutorCrash(Exception):
    """The (simulated or real) kernel crashed under this program; the
    console log is attached."""

    def __init__(self, log: str):
        super().__init__("kernel crash")
        self.log = log


_CALL_RESULT = struct.Struct("<8I")
_EXECUTE_REQ = struct.Struct("<5Q")
_EXECUTE_REP = struct.Struct("<3Q")
_HANDSHAKE_REQ = struct.Struct("<3Q")
_HANDSHAKE_REP = struct.Struct("<Q")


def build_executor(force: bool = False) -> Path:
    """Build the native executor if needed; returns the binary path."""
    if EXECUTOR_BIN.exists() and not force:
        src_mtime = max(p.stat().st_mtime for p in EXECUTOR_DIR.glob("*.cc"))
        hdr_mtime = max(p.stat().st_mtime for p in EXECUTOR_DIR.glob("*.h"))
        if EXECUTOR_BIN.stat().st_mtime >= max(src_mtime, hdr_mtime):
            return EXECUTOR_BIN
    subprocess.run(["make", "-s"], cwd=EXECUTOR_DIR, check=True,
                   capture_output=True)
    return EXECUTOR_BIN


class Env:
    """One executor process + its shmem files (reference: ipc.go MakeEnv).

    Respawn-on-failure: exec() transparently restarts a dead executor
    up to `max_restarts` times before raising (reference:
    syz-fuzzer/proc.go:269-277 retries, ipc.go:307-313 respawn).
    """

    def __init__(self, pid: int, env_flags: EnvFlags,
                 workdir: Optional[str] = None, executor: Optional[Path] = None,
                 timeout_s: float = 60.0):
        self.pid = pid
        self.env_flags = env_flags
        self.timeout_s = timeout_s
        self.executor = Path(executor) if executor else build_executor()
        self._tmp = tempfile.TemporaryDirectory(
            prefix=f"tz-ipc-{pid}-", dir=workdir)
        d = Path(self._tmp.name)
        self.in_path = d / "in"
        self.out_path = d / "out"
        self.err_path = d / "console"
        self.in_path.write_bytes(b"\x00" * IN_SHMEM_SIZE)
        self.out_path.write_bytes(b"\x00" * OUT_SHMEM_SIZE)
        self._in_file = open(self.in_path, "r+b")
        self._out_file = open(self.out_path, "r+b")
        self._in_mm = mmap.mmap(self._in_file.fileno(), IN_SHMEM_SIZE)
        self._out_mm = mmap.mmap(self._out_file.fileno(), OUT_SHMEM_SIZE)
        self._proc: Optional[subprocess.Popen] = None
        self._err_file = None
        self.stat_execs = 0
        self.stat_restarts = 0

    # -- process lifecycle ------------------------------------------------

    def _spawn(self) -> None:
        self.close_proc()
        self._err_file = open(self.err_path, "wb")
        # bufsize=0: replies are read both via the file object (during
        # handshake) and via select+os.read on the raw fd (exec loop);
        # buffering would strand bytes invisible to select.
        self._proc = subprocess.Popen(
            [str(self.executor), str(self.in_path), str(self.out_path)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=self._err_file, bufsize=0)
        req = _HANDSHAKE_REQ.pack(HANDSHAKE_REQ_MAGIC, int(self.env_flags),
                                  self.pid)
        try:
            self._proc.stdin.write(req)
            self._proc.stdin.flush()
            rep = self._read_exact(_HANDSHAKE_REP.size)
        except (BrokenPipeError, ExecutorFailure):
            raise ExecutorFailure(
                f"executor handshake failed: {self.console_tail()}")
        (magic,) = _HANDSHAKE_REP.unpack(rep)
        if magic != HANDSHAKE_REP_MAGIC:
            raise ExecutorFailure(f"bad handshake reply {magic:#x}")

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._proc.stdout.read(n - len(buf))
            if not chunk:
                raise ExecutorFailure("executor pipe closed")
            buf += chunk
        return buf

    def close_proc(self) -> None:
        if self._proc is not None:
            self._proc.kill()
            self._proc.wait()
            self._proc = None
        if self._err_file is not None:
            self._err_file.close()
            self._err_file = None

    def close(self) -> None:
        self.close_proc()
        self._in_mm.close()
        self._out_mm.close()
        self._in_file.close()
        self._out_file.close()
        self._tmp.cleanup()

    def console_tail(self, nbytes: int = 1 << 16) -> str:
        try:
            data = self.err_path.read_bytes()
        except FileNotFoundError:
            return ""
        return data[-nbytes:].decode("utf-8", "replace")

    # -- execution --------------------------------------------------------

    def exec(self, opts: ExecOpts, prog_data,
             max_restarts: int = 3) -> ExecResult:
        """Execute one serialized program (exec wire format).

        prog_data is any bytes-like buffer; device mutants hand the
        (offset, length) memoryview of their batch output arena
        straight through (ops/emit), so the program bytes are copied
        exactly once — into the executor's shmem mapping below."""
        if len(prog_data) > IN_SHMEM_SIZE:
            raise ValueError("program exceeds exec buffer")
        last_exc: Optional[Exception] = None
        for _ in range(max_restarts + 1):
            try:
                if self._proc is None or self._proc.poll() is not None:
                    self._spawn()
                    self.stat_restarts += 1
                return self._exec_once(opts, prog_data)
            except ExecutorCrash:
                # The session is dead; drop it now so the next exec
                # respawns with a truncated console (otherwise the old
                # BUG output is mis-attributed to the next program).
                self.close_proc()
                raise
            except ExecutorFailure as e:
                last_exc = e
                self.close_proc()
        raise last_exc  # type: ignore[misc]

    def _exec_once(self, opts: ExecOpts, prog_data) -> ExecResult:
        self._in_mm.seek(0)
        self._in_mm.write(prog_data)  # accepts any buffer, one memcpy
        self.stat_execs += 1
        req = _EXECUTE_REQ.pack(
            EXECUTE_REQ_MAGIC, int(opts.flags), len(prog_data) // 8,
            opts.fault_call & 0xFFFFFFFFFFFFFFFF, opts.fault_nth)
        try:
            self._proc.stdin.write(req)
            self._proc.stdin.flush()
        except BrokenPipeError:
            self._raise_dead()
        deadline = time.monotonic() + self.timeout_s
        rep = self._read_reply(deadline)
        magic, status, ncalls = _EXECUTE_REP.unpack(rep)
        if magic != EXECUTE_REP_MAGIC:
            raise ExecutorFailure(f"bad execute reply magic {magic:#x}")
        if status != 0:
            raise ExecutorFailure(f"executor status {status}")
        return self._parse_output()

    def _read_reply(self, deadline: float) -> bytes:
        # The executor enforces per-call timeouts itself, so a silent
        # executor means death or a wedge; select() keeps the deadline
        # enforceable either way (reference: ipc.go:760-812 hang logic).
        import select

        fd = self._proc.stdout.fileno()
        buf = b""
        while len(buf) < _EXECUTE_REP.size:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise ExecutorFailure("executor timed out")
            ready, _, _ = select.select([fd], [], [], min(remaining, 1.0))
            if not ready:
                if self._proc.poll() is not None:
                    self._raise_dead()
                continue
            chunk = os.read(fd, _EXECUTE_REP.size - len(buf))
            if not chunk:
                self._raise_dead()
            buf += chunk
        return buf

    def _raise_dead(self):
        # Stdout EOF/BrokenPipe can precede waitpid observability by a
        # hair; reap properly so the exit status is real.
        try:
            code = self._proc.wait(timeout=2.0)
        except subprocess.TimeoutExpired:
            code = self._proc.poll()
        log = self.console_tail()
        if "BUG:" in log or "WARNING:" in log or code == STATUS_ERROR:
            raise ExecutorCrash(log)
        raise ExecutorFailure(f"executor died (status {code}): {log[-500:]}")

    def _parse_output(self) -> ExecResult:
        mm = self._out_mm
        ncalls, completed = struct.unpack_from("<2I", mm, 0)
        off = 8
        infos: list[CallInfo] = []
        for _ in range(ncalls):
            (ci, cid, err, flags, slen, covlen, compslen, _r) = \
                _CALL_RESULT.unpack_from(mm, off)
            off += _CALL_RESULT.size
            signal = np.frombuffer(mm, np.uint32, slen, off).copy()
            off += 4 * slen
            cover = np.frombuffer(mm, np.uint32, covlen, off).copy()
            off += 4 * covlen
            comps_arr = np.frombuffer(mm, np.uint64, 2 * compslen, off)
            off += 16 * compslen
            comps = [(int(comps_arr[2 * i]), int(comps_arr[2 * i + 1]))
                     for i in range(compslen)]
            infos.append(CallInfo(call_index=ci, call_id=cid, errno=err,
                                  flags=CallFlags(flags), signal=signal,
                                  cover=cover, comps=comps))
        return ExecResult(info=infos, completed=bool(completed))


def make_env(pid: int = 0, sim: bool = True, signal: bool = True,
             debug: bool = False, fork_prog: Optional[bool] = None,
             sandbox: str = "none", tun: bool = False,
             cgroups: bool = False, **kw) -> Env:
    flags = {
        "none": EnvFlags.SANDBOX_NONE,
        "setuid": EnvFlags.SANDBOX_SETUID,
        "namespace": EnvFlags.SANDBOX_NAMESPACE,
    }[sandbox]
    if sim:
        flags |= EnvFlags.SIM_OS
    if signal:
        flags |= EnvFlags.SIGNAL
    if debug:
        flags |= EnvFlags.DEBUG
    if tun:
        flags |= EnvFlags.ENABLE_TUN
    if cgroups:
        flags |= EnvFlags.ENABLE_CGROUPS
    # Real-OS programs mutate process state (fds, maps, signal
    # dispositions) and may plain _exit: isolate each in a fork by
    # default.  The sim backend keeps the faster in-process model.
    if fork_prog is None:
        fork_prog = not sim
    if fork_prog:
        flags |= EnvFlags.FORK_PROG
    return Env(pid, flags, **kw)
