"""Python-side model of the simulated kernel's deterministic maps.

Mirrors executor/sim_kernel.h so tests and the repro pipeline can
predict which (call_id, args) combinations unlock magic edges or the
two-stage crash — the executable ground truth the reference only has
against a live kernel.
"""

from __future__ import annotations

MASK64 = (1 << 64) - 1


def splitmix64(x: int) -> int:
    x = (x + 0x9E3779B97F4A7C15) & MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & MASK64
    return x ^ (x >> 31)


def call_hash(call_id: int) -> int:
    return splitmix64((call_id * 0x10001 + 1) & MASK64)


def is_crashy(call_id: int) -> bool:
    """1-in-8 call ids have the two-stage crash trigger
    (executor/sim_kernel.h crash block)."""
    return (call_hash(call_id) & 7) == 3


def crash_magics(call_id: int) -> tuple[int, int]:
    """(arg0, arg1) values that crash a crashy call."""
    h = call_hash(call_id)
    c0 = splitmix64((h ^ 0xC0DE0000) & MASK64) & 0xFFFFFFFF
    c1 = splitmix64((h ^ 0xC0DE0001) & MASK64) & 0xFFFFFFFF
    return c0, c1


def arg_magic(call_id: int, arg_index: int) -> int:
    """Per-(call,arg) comparison magic that unlocks bonus edges."""
    h = call_hash(call_id)
    return splitmix64((h + 0x1111 * (arg_index + 1)) & MASK64) & 0xFFFFFFFF


RACE_PREPARE_TAG = 5
RACE_TRIGGER_TAG = 9


def race_tag(call_id: int) -> int:
    """Race-window family tag (executor/sim_kernel.h race families)."""
    return call_hash(call_id) & 31


def is_race_prepare(call_id: int) -> bool:
    return race_tag(call_id) == RACE_PREPARE_TAG


def is_race_trigger(call_id: int) -> bool:
    return race_tag(call_id) == RACE_TRIGGER_TAG
