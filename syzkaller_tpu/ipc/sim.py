"""Python-side model of the simulated kernel's deterministic maps.

Mirrors executor/sim_kernel.h so tests and the repro pipeline can
predict which (call_id, args) combinations unlock magic edges or the
two-stage crash — the executable ground truth the reference only has
against a live kernel.

The lower half of the module is the FIXED-SLOT execution model the
on-device simulated executor (syzkaller_tpu/sim) is parity-tested
against: every call's possible edges are laid out in a static
SIM_EDGE_SLOTS-wide vector (entry, per-arg bucket, per-arg magic
pair, per-arg handle, the two combo edges, the crash-arm edge) with a
validity mask instead of the C++ append-order cov buffer.  The slot
layout is a pure re-indexing of sim_kernel.h's emit() sequence — the
same (pc, emitted?) pairs, order-independent — which is what lets a
batched device kernel with static shapes be bit-exact with the host
model edge for edge.
"""

from __future__ import annotations

MASK64 = (1 << 64) - 1


def splitmix64(x: int) -> int:
    x = (x + 0x9E3779B97F4A7C15) & MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & MASK64
    return x ^ (x >> 31)


def call_hash(call_id: int) -> int:
    return splitmix64((call_id * 0x10001 + 1) & MASK64)


def is_crashy(call_id: int) -> bool:
    """1-in-8 call ids have the two-stage crash trigger
    (executor/sim_kernel.h crash block)."""
    return (call_hash(call_id) & 7) == 3


def crash_magics(call_id: int) -> tuple[int, int]:
    """(arg0, arg1) values that crash a crashy call."""
    h = call_hash(call_id)
    c0 = splitmix64((h ^ 0xC0DE0000) & MASK64) & 0xFFFFFFFF
    c1 = splitmix64((h ^ 0xC0DE0001) & MASK64) & 0xFFFFFFFF
    return c0, c1


def arg_magic(call_id: int, arg_index: int) -> int:
    """Per-(call,arg) comparison magic that unlocks bonus edges."""
    h = call_hash(call_id)
    return splitmix64((h + 0x1111 * (arg_index + 1)) & MASK64) & 0xFFFFFFFF


RACE_PREPARE_TAG = 5
RACE_TRIGGER_TAG = 9


def race_tag(call_id: int) -> int:
    """Race-window family tag (executor/sim_kernel.h race families)."""
    return call_hash(call_id) & 31


def is_race_prepare(call_id: int) -> bool:
    return race_tag(call_id) == RACE_PREPARE_TAG


def is_race_trigger(call_id: int) -> bool:
    return race_tag(call_id) == RACE_TRIGGER_TAG


def is_lockless(call_id: int) -> bool:
    """Calls the executor routes through exec_lockless (the race
    families): entry edge only, never touch the handle set."""
    t = race_tag(call_id)
    return t == RACE_PREPARE_TAG or t == RACE_TRIGGER_TAG


# ---- fixed-slot edge layout (the device sim-exec contract) -----------

#: executor cap (wire nargs > 8 is failf'd, executor.cc:712).
SIM_MAX_ARGS = 8

#: Slot indices into a call's SIM_EDGE_SLOTS-wide edge vector.  The
#: layout is static so a batched kernel needs no compaction: slot 0
#: is the unconditional entry edge, 1..8 the per-arg value-bucket
#: edges, 9..24 the per-arg magic-unlock PAIRS (two consecutive slots
#: per arg), 25..32 the per-arg valid-handle edges, 33/34 the two
#: state-combo edges, 35 the crash-ARM edge (arg0 hit its crash magic
#: but arg1 did not complete the crash).
SIM_SLOT_ENTRY = 0
SIM_SLOT_BUCKET0 = 1
SIM_SLOT_MAGIC0 = SIM_SLOT_BUCKET0 + SIM_MAX_ARGS  # 9
SIM_SLOT_HANDLE0 = SIM_SLOT_MAGIC0 + 2 * SIM_MAX_ARGS  # 25
SIM_SLOT_COMBO_HANDLES = SIM_SLOT_HANDLE0 + SIM_MAX_ARGS  # 33
SIM_SLOT_COMBO_MIXED = SIM_SLOT_COMBO_HANDLES + 1  # 34
SIM_SLOT_CRASH_ARM = SIM_SLOT_COMBO_MIXED + 1  # 35
SIM_EDGE_SLOTS = SIM_SLOT_CRASH_ARM + 1  # 36


def value_bucket(v: int) -> int:
    """Coarse value bucket (sim_kernel.h value_bucket): log2 magnitude
    in the high bits, the low nibble verbatim."""
    v &= MASK64
    log2 = 0
    while log2 < 63 and (v >> (log2 + 1)):
        log2 += 1
    return (log2 << 4) | (v & 0xF)


def edge_pc(seed: int) -> int:
    """One emitted edge PC: the low 32 bits of splitmix64(seed)
    (sim_kernel.h emit())."""
    return splitmix64(seed & MASK64) & 0xFFFFFFFF


class SimCallResult:
    """One executed call in the fixed-slot layout.

    edges[k] is slot k's PC (always computed), valid[k] whether the
    simulated kernel actually emitted it.  A fully-crashed call
    reports NO edges (valid all False): the executor _exits before
    copying the call's coverage out (executor.cc run loop), so the
    real pipeline never sees them either."""

    __slots__ = ("edges", "valid", "ret", "errno", "crashed")

    def __init__(self, edges, valid, ret, errno, crashed):
        self.edges = edges
        self.valid = valid
        self.ret = ret
        self.errno = errno
        self.crashed = crashed

    def emitted(self) -> list[int]:
        """The valid edge PCs (order = slot order)."""
        return [pc for pc, ok in zip(self.edges, self.valid) if ok]


class SimKernelModel:
    """Stateful host mirror of sim_kernel.h's SimKernel for SEQUENTIAL
    execution: the handle set accumulates across exec() calls exactly
    like the C++ std::set, the race families run the lockless path
    (which sequentially can never crash — prepare closes its window
    before returning), and fault injection is never armed (the
    prescore path does not model it)."""

    def __init__(self, pid: int = 0):
        self.pid = pid
        self.handles: set[int] = set()

    def exec(self, call_id: int, args) -> SimCallResult:
        call_id &= 0xFFFFFFFF
        args = [a & MASK64 for a in args[:SIM_MAX_ARGS]]
        nargs = len(args)
        h = call_hash(call_id)
        edges = [0] * SIM_EDGE_SLOTS
        valid = [False] * SIM_EDGE_SLOTS
        edges[SIM_SLOT_ENTRY] = edge_pc(h)
        valid[SIM_SLOT_ENTRY] = True
        for i in range(SIM_MAX_ARGS):
            a = args[i] if i < nargs else 0
            edges[SIM_SLOT_BUCKET0 + i] = edge_pc(
                h ^ splitmix64((i << 32) | value_bucket(a)))
            edges[SIM_SLOT_MAGIC0 + 2 * i] = edge_pc(
                h ^ splitmix64(0xABCD0000 + i))
            edges[SIM_SLOT_MAGIC0 + 2 * i + 1] = edge_pc(
                h ^ splitmix64(0xABCD1000 + i
                               + (arg_magic(call_id, i) & 0xFF)))
            edges[SIM_SLOT_HANDLE0 + i] = edge_pc(
                h ^ splitmix64(0xFEED0000 + i))
        edges[SIM_SLOT_COMBO_HANDLES] = edge_pc(h ^ 0x10)
        edges[SIM_SLOT_COMBO_MIXED] = edge_pc(h ^ 0x11)
        edges[SIM_SLOT_CRASH_ARM] = edge_pc(h ^ 0xDEAD0)

        if is_lockless(call_id):
            # exec_lockless: entry edge only, the handle set is never
            # touched, and a sequential trigger finds the window
            # closed — ret 0, errno 0, no crash.
            return SimCallResult(edges, valid, 0, 0, False)

        magic_hits = 0
        handle_hits = 0
        for i, a in enumerate(args):
            valid[SIM_SLOT_BUCKET0 + i] = True
            if a == arg_magic(call_id, i):
                magic_hits += 1
                valid[SIM_SLOT_MAGIC0 + 2 * i] = True
                valid[SIM_SLOT_MAGIC0 + 2 * i + 1] = True
            if a in self.handles:
                handle_hits += 1
                valid[SIM_SLOT_HANDLE0 + i] = True
        valid[SIM_SLOT_COMBO_HANDLES] = handle_hits >= 2
        valid[SIM_SLOT_COMBO_MIXED] = handle_hits >= 1 and magic_hits >= 1

        if (h & 7) == 3 and nargs >= 2:
            c0, c1 = crash_magics(call_id)
            if args[0] == c0:
                valid[SIM_SLOT_CRASH_ARM] = True
                if args[1] == c1:
                    # Full crash: the executor _exits before copyout,
                    # so neither the edges nor the ret survive.
                    return SimCallResult(edges,
                                         [False] * SIM_EDGE_SLOTS,
                                         0, 0, True)

        if (h & 3) == 1:
            handle = 0x1000 + (len(self.handles) * 4 + self.pid) % 0xFFFFF
            self.handles.add(handle)
            return SimCallResult(edges, valid, handle, 0, False)
        wants_handle = (h & 3) == 2 and nargs > 0
        errno = 9 if (wants_handle and handle_hits == 0) else 0
        return SimCallResult(edges, valid, 0, errno, False)
