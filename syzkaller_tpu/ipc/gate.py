"""Gate: sliding-window concurrency limiter with a periodic
stop-the-world callback.

Semantics follow the reference (reference: pkg/ipc/gate.go:23-76): at
most `capacity` callers are inside the gate; every full window the
gate drains and runs `stop_cb` alone (used for kmemleak-style scans
that need the machine quiet).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional


class Gate:
    def __init__(self, capacity: int,
                 stop_cb: Optional[Callable[[], None]] = None):
        assert capacity > 0
        self.capacity = capacity
        self.stop_cb = stop_cb
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._inside = 0
        self._since_stop = 0
        self._stopping = False

    def enter(self) -> None:
        with self._cv:
            while self._stopping or self._inside >= self.capacity:
                self._cv.wait()
            self._inside += 1

    def leave(self) -> None:
        run_stop = False
        with self._cv:
            assert self._inside > 0
            self._inside -= 1
            self._since_stop += 1
            if self.stop_cb is not None and \
                    self._since_stop >= self.capacity and not self._stopping:
                self._stopping = True
                run_stop = True
            self._cv.notify_all()
        if run_stop:
            with self._cv:
                while self._inside > 0:
                    self._cv.wait()
            try:
                self.stop_cb()
            finally:
                with self._cv:
                    self._stopping = False
                    self._since_stop = 0
                    self._cv.notify_all()

    def __enter__(self):
        self.enter()
        return self

    def __exit__(self, *exc):
        self.leave()
        return False
