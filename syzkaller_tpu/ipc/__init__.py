"""Executor IPC: spawning and driving native tz-executor processes.

Mirrors the role of the reference pkg/ipc (reference: pkg/ipc/ipc.go):
mem-mapped in/out files, fork-server handshake over pipes, per-program
execute requests, output shmem parsing into per-call results, magic
exit statuses, and the Gate concurrency window.
"""

from syzkaller_tpu.ipc.env import (  # noqa: F401
    CallFlags,
    CallInfo,
    Env,
    EnvFlags,
    ExecFlags,
    ExecOpts,
    ExecResult,
    ExecutorCrash,
    ExecutorFailure,
    build_executor,
    make_env,
)
from syzkaller_tpu.ipc.gate import Gate  # noqa: F401
