from syzkaller_tpu.db.db import DB, Record, open_db

__all__ = ["DB", "Record", "open_db"]
