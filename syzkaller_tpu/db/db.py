"""Append-only compacting key-value store.

Persistence layer for the corpus (`corpus.db`) and hub state.  Writes
append compressed records to the end of the file; records with an
existing key supersede it (or delete it when the value is empty and
seq is the tombstone).  When the dead-byte ratio grows past 10x the
live size the file is compacted by rewriting in place via a temp file.
Corrupted tails (e.g. from a crash mid-append) are dropped on open.

Reference: pkg/db/db.go:25-140 (Open/Save/Delete/Flush/compaction),
record framing db.go:142-229 (flate-compressed key/seq/val records).
"""

from __future__ import annotations

import os
import struct
import time
import zlib
from dataclasses import dataclass
from typing import Optional

from syzkaller_tpu import telemetry
from syzkaller_tpu.health.envsafe import env_int
from syzkaller_tpu.health.faultinject import fault_point

MAGIC = 0x745A6462  # "tzdb"
CUR_VERSION = 1

#: fsync latency on the append path — the price of "a record
#: acknowledged to a fuzzer survives a crash" (TZ_DB_FSYNC=0 trades
#: it back for throughput on expendable corpora).
_H_FSYNC = telemetry.histogram(
    "tz_db_fsync_seconds", "corpus DB fsync latency on flush")

_HDR = struct.Struct("<II")  # magic, version
_REC = struct.Struct("<I")  # compressed record length
_REC_BODY = struct.Struct("<IQ")  # key length, seq

DELETE_SEQ = 0xFFFFFFFFFFFFFFFF


@dataclass
class Record:
    val: bytes
    seq: int


class DB:
    """Append-only compacting KV store (reference: pkg/db/db.go:25).

    `version` is a user payload stamped in the header — the manager
    uses it to decide re-minimize/re-smash policy on format upgrades
    (reference: syz-manager/manager.go:192-207).
    """

    def __init__(self, filename: str, records: dict[str, Record],
                 version: int, uncompacted: int):
        import threading

        self.filename = filename
        self.version = version
        self.records = records
        self.pending: dict[str, Optional[Record]] = {}
        self._uncompacted = uncompacted
        # save/flush are called from concurrent RPC handler threads
        # (manager NewInput); all mutation is serialized here.
        self._lock = threading.RLock()

    def save(self, key: str, val: bytes, seq: int) -> None:
        if seq == DELETE_SEQ:
            raise ValueError("reserved seq")
        with self._lock:
            self.records[key] = Record(val, seq)
            self.pending[key] = Record(val, seq)

    def delete(self, key: str) -> None:
        with self._lock:
            self.records.pop(key, None)
            self.pending[key] = None

    def flush(self) -> None:
        """Append pending records; compact if the file has grown past
        10x the live record count (reference: db.go:83-104).

        The append is durable: flush + fsync before pending clears
        (TZ_DB_FSYNC=0 opts out), so a record acknowledged to a
        fuzzer (manager NewInput calls save+flush before replying)
        survives a crash.  The db.append seam fires per record; a
        scripted fault propagates with `pending` intact, so the
        records written so far are simply re-appended by the next
        flush (supersede-by-key makes the duplicates harmless)."""
        with self._lock:
            if self._uncompacted >= 10 * max(len(self.records), 1) + 10:
                self._compact()
                return
            if not self.pending:
                return
            with open(self.filename, "ab") as f:
                for key, rec in self.pending.items():
                    fault_point("db.append")
                    f.write(_serialize_record(key, rec))
                f.flush()
                if env_int("TZ_DB_FSYNC", 1):
                    t0 = time.monotonic()
                    os.fsync(f.fileno())
                    _H_FSYNC.observe(time.monotonic() - t0)
            self._uncompacted += len(self.pending)
            self.pending.clear()

    def bump_version(self, version: int) -> None:
        """Rewrite with a new header version (reference: db.go:106-112)."""
        with self._lock:
            self.version = version
            self._compact()

    def _compact(self) -> None:
        tmp = self.filename + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_HDR.pack(MAGIC, self.version))
            for key, rec in self.records.items():
                f.write(_serialize_record(key, rec))
            f.flush()
            os.fsync(f.fileno())
        # Seam between the complete tmp and the publish: a scripted
        # fault models a crash mid-compaction — the old file stays
        # authoritative and open_db unlinks the orphaned tmp.
        fault_point("db.compact")
        os.replace(tmp, self.filename)
        self._uncompacted = len(self.records)
        self.pending.clear()


def _serialize_record(key: str, rec: Optional[Record]) -> bytes:
    kb = key.encode()
    if rec is None:
        body = _REC_BODY.pack(len(kb), DELETE_SEQ) + kb
    else:
        body = _REC_BODY.pack(len(kb), rec.seq) + kb + rec.val
    comp = zlib.compress(body, 6)
    return _REC.pack(len(comp)) + comp


def open_db(filename: str, version: int = CUR_VERSION) -> DB:
    """Open or create; tolerates a corrupted tail by truncating to the
    last whole record (reference: db.go:40-81 deserializeDB)."""
    records: dict[str, Record] = {}
    file_version = version
    uncompacted = 0
    # A crash between _compact's fsync and its rename orphans the tmp;
    # left in place it would shadow disk space forever (and a partial
    # one must never be mistaken for the real DB).
    stale_tmp = filename + ".tmp"
    if os.path.exists(stale_tmp):
        try:
            os.unlink(stale_tmp)
        except OSError:
            pass
    if os.path.exists(filename) and os.path.getsize(filename) >= _HDR.size:
        with open(filename, "rb") as f:
            data = f.read()
        magic, ver = _HDR.unpack_from(data, 0)
        if magic == MAGIC:
            file_version = ver
        else:
            # Header corrupted: records are individually checksummed by
            # zlib, so still try to recover them, and rewrite a clean
            # header in place rather than discarding the corpus.
            with open(filename, "r+b") as f:
                f.write(_HDR.pack(MAGIC, version))
        pos = _HDR.size
        good = pos
        while pos + _REC.size <= len(data):
            (clen,) = _REC.unpack_from(data, pos)
            if pos + _REC.size + clen > len(data):
                break
            try:
                body = zlib.decompress(data[pos + _REC.size:
                                            pos + _REC.size + clen])
                klen, seq = _REC_BODY.unpack_from(body, 0)
                key = body[_REC_BODY.size:_REC_BODY.size + klen].decode()
                val = body[_REC_BODY.size + klen:]
            except Exception:
                break
            if seq == DELETE_SEQ:
                records.pop(key, None)
            else:
                records[key] = Record(val, seq)
            pos += _REC.size + clen
            good = pos
            uncompacted += 1
        if good < len(data):
            with open(filename, "r+b") as f:
                f.truncate(good)
    else:
        with open(filename, "wb") as f:
            f.write(_HDR.pack(MAGIC, version))
    db = DB(filename, records, file_version, uncompacted)
    return db
