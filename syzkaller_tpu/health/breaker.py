"""Circuit breaker for the device mutation engine.

Replaces the ad-hoc `errors_since_ok` counter in
DevicePipeline._worker_loop, whose rebuild latch fired exactly once
(at error #4) and whose backoff was interleaved with normal dispatch.
The breaker makes the health state machine explicit:

  closed     normal operation; a streak of `failure_threshold`
             consecutive failures trips it open,
  open       the device is presumed down: no dispatch, in-flight work
             dropped, consumers demote to the CPU engine.  Probes are
             scheduled with exponential backoff + deterministic
             jitter,
  half_open  one probe batch in flight.  Entering half-open marks a
             host-snapshot rebuild pending (EVERY re-entry, not just
             the first — the r5 one-shot-latch bug), so a backend
             that restarted mid-streak always gets a fresh ring,
  closed     a successful probe re-promotes and resets the backoff.

Every transition is counted (BreakerCounters) so tests can assert the
exact trajectory and the manager status page can show it.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field

from syzkaller_tpu import telemetry

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# Process-wide transition counters (syzkaller_tpu/telemetry): the same
# numbers the BreakerCounters dataclass tracks per instance, folded
# into the one registry /metrics and bench_watch read.  Registered at
# import so a manager-only process still exposes them at zero.
_M_OPENS = telemetry.counter(
    "tz_breaker_opens_total", "breaker transitions to open")
_M_CLOSES = telemetry.counter(
    "tz_breaker_closes_total", "breaker re-promotions to closed")
_M_HALF_OPENS = telemetry.counter(
    "tz_breaker_half_opens_total", "probe windows entered")
_M_REBUILDS = telemetry.counter(
    "tz_breaker_rebuilds_total", "host-snapshot ring rebuilds consumed")
_M_FAILURES = telemetry.counter(
    "tz_breaker_failures_total", "device failures recorded")
_M_SUCCESSES = telemetry.counter(
    "tz_breaker_successes_total", "device successes recorded")


@dataclass
class BreakerCounters:
    opens: int = 0  # transitions to open, incl. failed-probe reopens
    closes: int = 0  # re-promotions (half-open probe succeeded)
    half_opens: int = 0  # probe windows entered
    rebuilds: int = 0  # host-snapshot rebuilds consumed
    failures: int = 0  # failures recorded (any state)
    successes: int = 0  # successes recorded (any state)

    def as_dict(self) -> dict[str, int]:
        return {
            "opens": self.opens,
            "closes": self.closes,
            "half_opens": self.half_opens,
            "rebuilds": self.rebuilds,
            "failures": self.failures,
            "successes": self.successes,
        }


class CircuitBreaker:
    """Thread-safe; driven by the single pipeline worker, read by
    consumers (PipelineMutator fast-demote) and the status page.

    `clock` and `seed` are injectable so tests get deterministic
    backoff trajectories without sleeping real time."""

    def __init__(self, failure_threshold: int = 4,
                 backoff_initial: float = 1.0,
                 backoff_cap: float = 60.0,
                 jitter: float = 0.1,
                 seed: int = 0,
                 clock=time.monotonic):
        self.failure_threshold = max(1, failure_threshold)
        self.backoff_initial = backoff_initial
        self.backoff_cap = backoff_cap
        self.jitter = jitter
        self._rng = random.Random(seed)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consec_failures = 0
        self._backoff = backoff_initial
        self._next_probe_at = 0.0
        self._rebuild_pending = False
        self.counters = BreakerCounters()
        # Wallclock transition timestamps (0.0 = never): the timeline
        # anchors bench_watch's wedge diagnostics correlate against
        # logs, so these are time.time(), not the injected clock.
        self._last_open_at = 0.0
        self._last_close_at = 0.0
        self._last_half_open_at = 0.0

    def configure_backoff(self, initial: float = None,
                          cap: float = None) -> None:
        """Retune the probe backoff (tests, deployments).  Takes
        effect immediately when the breaker is not mid-backoff."""
        with self._lock:
            if initial is not None:
                self.backoff_initial = initial
                if self._state == CLOSED:
                    self._backoff = initial
            if cap is not None:
                self.backoff_cap = cap
                self._backoff = min(self._backoff, cap)

    # -- reads ------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def is_open(self) -> bool:
        """True while the device engine is demoted (open or probing)."""
        with self._lock:
            return self._state != CLOSED

    def seconds_until_probe(self) -> float:
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(0.0, self._next_probe_at - self._clock())

    def snapshot(self) -> dict:
        with self._lock:
            out = self.counters.as_dict()
            out["state"] = self._state
            out["consecutive_failures"] = self._consec_failures
            out["backoff_s"] = round(self._backoff, 3)
            out["last_open_at"] = round(self._last_open_at, 3)
            out["last_close_at"] = round(self._last_close_at, 3)
            out["last_half_open_at"] = round(self._last_half_open_at, 3)
            return out

    # -- the state machine ------------------------------------------------

    def allow(self) -> bool:
        """May the worker dispatch right now?  In open state this is
        the probe gate: once the backoff elapses it transitions to
        half-open (marking a rebuild pending) and admits one probe."""
        with self._lock:
            if self._state == CLOSED or self._state == HALF_OPEN:
                return True
            if self._clock() < self._next_probe_at:
                return False
            self._state = HALF_OPEN
            self.counters.half_opens += 1
            self._rebuild_pending = True
            self._last_half_open_at = time.time()
            _M_HALF_OPENS.inc()
            telemetry.record_event(
                "breaker.half_open",
                f"probe #{self.counters.half_opens}")
            return True

    def consume_rebuild(self) -> bool:
        """One host-snapshot rebuild per half-open entry: True exactly
        once after each open→half-open transition."""
        with self._lock:
            if not self._rebuild_pending:
                return False
            self._rebuild_pending = False
            self.counters.rebuilds += 1
            _M_REBUILDS.inc()
            telemetry.record_event(
                "breaker.rebuild", f"rebuild #{self.counters.rebuilds}")
            return True

    def record_failure(self) -> str:
        """Returns the state after accounting the failure."""
        _M_FAILURES.inc()
        tripped = False
        with self._lock:
            self.counters.failures += 1
            self._consec_failures += 1
            if self._state == CLOSED:
                if self._consec_failures < self.failure_threshold:
                    return self._state
                self._trip_locked()
                tripped = True
            elif self._state == HALF_OPEN:
                # Failed probe: back off harder and reopen.
                self._backoff = min(self._backoff * 2, self.backoff_cap)
                self._trip_locked()
                tripped = True
            else:  # already open (e.g. a straggler in-flight failure)
                self._next_probe_at = self._clock() + self._jittered()
            state = self._state
        if tripped:
            # Flight recorder: a trip to open is an incident boundary;
            # dump outside the breaker lock (file IO), rate-limited.
            telemetry.FLIGHT.dump(
                "breaker_open",
                f"after {self.counters.failures} recorded failures")
        return state

    def record_success(self) -> str:
        _M_SUCCESSES.inc()
        with self._lock:
            self.counters.successes += 1
            self._consec_failures = 0
            if self._state != CLOSED:
                self._state = CLOSED
                self.counters.closes += 1
                self._backoff = self.backoff_initial
                self._rebuild_pending = False
                self._last_close_at = time.time()
                _M_CLOSES.inc()
                telemetry.record_event(
                    "breaker.close",
                    f"re-promoted after {self.counters.opens} opens")
            return self._state

    def _trip_locked(self) -> None:
        self._state = OPEN
        self.counters.opens += 1
        self._next_probe_at = self._clock() + self._jittered()
        self._last_open_at = time.time()
        _M_OPENS.inc()
        telemetry.record_event(
            "breaker.open",
            f"after {self._consec_failures} consecutive failures, "
            f"backoff {self._backoff:.1f}s")

    def _jittered(self) -> float:
        # Deterministic jitter (seeded RNG): spreads probe storms
        # across workers without making test trajectories flaky.
        return self._backoff * (1.0 + self.jitter * self._rng.random())
