"""Deterministic fault injection for the host side of the TPU engine.

syzkaller's executor treats fault injection as a first-class
capability (fail_nth: "fail the Nth blocking point of this call").
This module is the same discipline applied to the engine's own seams:
every place the fuzzer touches the device, the RPC link, or the
worker queue is a *named seam*, and a plan scripts exactly which
invocations of which seam fail or hang:

    TZ_FAULT_PLAN=device.launch:fail@3,5;rpc.send_frame:hang@2

reads "fail the 3rd and 5th device launches, hang the 2nd RPC frame
send".  Occurrences are 1-based invocation indices per seam, counted
process-wide; `N-M` spans an inclusive range, so `fail@1-8` scripts
eight consecutive failures.  `@*` fires on every invocation until the
seam is healed.

Seams are free when no plan is installed (one attribute load + `is
None` test), so production hot paths pay nothing.

Modes:
  fail — raise FaultInjected (a ConnectionError subclass, so the RPC
         client's reconnect path and the pipeline worker's generic
         failure handling both see a realistic error),
  hang — block until the seam is healed or the plan reset, modeling a
         wedged PJRT call / stalled TCP peer.  The watchdog is what
         converts a scripted hang into DeviceWedged; a hang seam left
         unreleased holds only a daemon thread.
"""

from __future__ import annotations

import re
import threading
from typing import Optional

from syzkaller_tpu.utils import log

# The registry of seams the engine actually guards.  A plan may name
# others (future seams, downstream forks) — that logs a warning rather
# than failing, but tests should stick to these.
SEAMS = (
    "device.launch",
    "device.compile",
    "device.triage",
    "device.sim",
    "device.arena",
    "device.hints",
    "staging.h2d",
    "rpc.send_frame",
    "rpc.recv_frame",
    "rpc.reply_cache",
    "manager.lease_expire",
    "hub.sync",
    "queue.put",
    "mesh.shard_probe",
    "serve.compose",
    "durable.ckpt_write",
    "durable.wal_append",
    "db.append",
    "db.compact",
)

MODES = ("fail", "hang")

_RULE_RE = re.compile(
    r"^(?P<seam>[a-z0-9_.]+):(?P<mode>[a-z]+)@(?P<occ>[0-9,*-]+)$")


class FaultInjected(ConnectionError):
    """A scripted seam failure.  Subclasses ConnectionError so the
    transports under test exercise their real reconnect/retry paths
    instead of a synthetic exception type they would never see."""

    def __init__(self, seam: str, n: int):
        super().__init__(f"fault injected at {seam} (invocation #{n})")
        self.seam = seam
        self.n = n


class _Rule:
    __slots__ = ("mode", "occurrences", "always")

    def __init__(self, mode: str, occurrences: frozenset[int],
                 always: bool):
        self.mode = mode
        self.occurrences = occurrences
        self.always = always

    def fires_at(self, n: int) -> bool:
        return self.always or n in self.occurrences


def _parse_occurrences(spec: str) -> tuple[frozenset[int], bool]:
    if spec == "*":
        return frozenset(), True
    out: set[int] = set()
    for part in spec.split(","):
        lo, sep, hi = part.partition("-")
        try:
            if sep:
                a, b = int(lo), int(hi)
            else:
                a = b = int(lo)
        except ValueError:
            raise ValueError(f"bad occurrence spec {part!r}")
        if a < 1 or b < a:
            raise ValueError(f"bad occurrence range {part!r}")
        out.update(range(a, b + 1))
    if not out:
        raise ValueError(f"empty occurrence spec {spec!r}")
    return frozenset(out), False


class FaultPlan:
    """A parsed TZ_FAULT_PLAN: per-seam rules + invocation counters.

    Thread-safe; one plan is active process-wide (install_plan).
    heal(seam) removes a seam's rules and releases its hung threads —
    the test-side lever for "the backend recovered"."""

    def __init__(self, rules: Optional[dict[str, _Rule]] = None):
        self._rules: dict[str, _Rule] = dict(rules or {})
        self._counts: dict[str, int] = {}
        self._fired: dict[str, int] = {}
        self._lock = threading.Lock()
        self._releases: dict[str, threading.Event] = {
            seam: threading.Event() for seam in self._rules}

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        rules: dict[str, _Rule] = {}
        for clause in filter(None, (c.strip() for c in text.split(";"))):
            m = _RULE_RE.match(clause)
            if m is None:
                raise ValueError(f"bad fault clause {clause!r} "
                                 "(want seam:mode@occurrences)")
            seam, mode, occ = m.group("seam", "mode", "occ")
            if mode not in MODES:
                raise ValueError(f"unknown fault mode {mode!r} "
                                 f"(want one of {MODES})")
            if seam not in SEAMS:
                log.logf(0, "fault plan names unregistered seam %r "
                            "(known: %s)", seam, ", ".join(SEAMS))
            if seam in rules:
                raise ValueError(f"duplicate seam {seam!r} in plan")
            occurrences, always = _parse_occurrences(occ)
            rules[seam] = _Rule(mode, occurrences, always)
        if not rules:
            raise ValueError("empty fault plan")
        return cls(rules)

    # -- introspection (tests) --------------------------------------------

    def invocations(self, seam: str) -> int:
        with self._lock:
            return self._counts.get(seam, 0)

    def fired(self, seam: str) -> int:
        with self._lock:
            return self._fired.get(seam, 0)

    # -- runtime ----------------------------------------------------------

    def heal(self, seam: str) -> None:
        """Stop injecting at this seam and release its hung threads."""
        with self._lock:
            self._rules.pop(seam, None)
            ev = self._releases.get(seam)
        if ev is not None:
            ev.set()

    def release_all(self) -> None:
        for ev in self._releases.values():
            ev.set()

    def hit(self, seam: str) -> None:
        """One invocation of `seam`; fail/hang per the plan."""
        with self._lock:
            n = self._counts.get(seam, 0) + 1
            self._counts[seam] = n
            rule = self._rules.get(seam)
            if rule is None or not rule.fires_at(n):
                return
            self._fired[seam] = self._fired.get(seam, 0) + 1
            mode = rule.mode
            ev = self._releases[seam]
        if mode == "fail":
            raise FaultInjected(seam, n)
        log.logf(2, "fault plan: hanging %s invocation #%d", seam, n)
        ev.wait()


_active: Optional[FaultPlan] = None
_env_loaded = False
_install_lock = threading.Lock()


def install_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Activate a plan process-wide (None deactivates); returns it."""
    global _active, _env_loaded
    with _install_lock:
        prev = _active
        _active = plan
        _env_loaded = True  # an explicit install overrides the env
    if prev is not None and prev is not plan:
        prev.release_all()
    return plan


def reset_plan() -> None:
    """Deactivate the plan and release any hung seams (test teardown)."""
    install_plan(None)


def plan_from_env() -> Optional[FaultPlan]:
    """Parse TZ_FAULT_PLAN; a malformed plan logs and is ignored (the
    harness must never take the engine down by itself)."""
    import os

    text = os.environ.get("TZ_FAULT_PLAN", "")
    if not text:
        return None
    try:
        return FaultPlan.parse(text)
    except ValueError as e:
        log.logf(0, "ignoring malformed TZ_FAULT_PLAN: %s", e)
        return None


def _load_env_plan() -> Optional[FaultPlan]:
    global _active, _env_loaded
    with _install_lock:
        if not _env_loaded:
            _env_loaded = True
            _active = plan_from_env()
        return _active


def fault_point(seam: str) -> None:
    """The per-seam hook.  No active plan: one global load + None
    test.  With a plan: count the invocation and fail/hang on script."""
    plan = _active
    if plan is None:
        if _env_loaded:
            return
        plan = _load_env_plan()
        if plan is None:
            return
    plan.hit(seam)
