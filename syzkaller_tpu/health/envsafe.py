"""Defensive TZ_* env parsing.

A malformed operator-supplied value (`TZ_PIPELINE_DISPATCH_DEPTH=two`)
must degrade to the compiled-in default, not kill fuzzer startup with
a ValueError half-way through DevicePipeline.__init__ — a fuzzer that
boots with a default knob finds bugs; one that dies on a typo in a
supervisor script finds nothing.
"""

from __future__ import annotations

import os

from syzkaller_tpu.utils import log


def _env_num(name: str, default, conv):
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return conv(raw)
    except (ValueError, TypeError):
        log.logf(0, "ignoring malformed %s=%r (using default %r)",
                 name, raw, default)
        return default


def env_int(name: str, default: int) -> int:
    return _env_num(name, default, lambda s: int(s, 0))


def env_float(name: str, default: float) -> float:
    return _env_num(name, default, float)
