"""Defensive TZ_* env parsing.

A malformed operator-supplied value (`TZ_PIPELINE_DISPATCH_DEPTH=two`)
must degrade to the compiled-in default, not kill fuzzer startup with
a ValueError half-way through DevicePipeline.__init__ — a fuzzer that
boots with a default knob finds bugs; one that dies on a typo in a
supervisor script finds nothing.

The companion failure mode is the knob that parses fine but is spelled
wrong (`TZ_TRIAGE_DISPACH_DEPTH=1`): it silently does nothing and the
operator believes the kill path is armed.  `warn_unknown_tz_vars`
closes that gap — engine start scans the environment for `TZ_*` names
outside the known-knob registry and logs each once per process.
"""

from __future__ import annotations

import os
import threading

from syzkaller_tpu.utils import log

#: Every TZ_* variable the engine understands.  env_int/env_float/
#: env_auto_int self-register the names they parse, but the static
#: seed below is what makes the typo guard correct at ENGINE START —
#: a knob whose parse site runs later (bench-only budgets, the trace
#: exporter) must not be flagged just because nothing read it yet.
KNOWN_TZ_VARS: set[str] = {
    "TZ_ARENA_DEVICE",
    "TZ_ARENA_DISTILL_EVERY",
    "TZ_ARENA_DISTILL_ROWS",
    "TZ_ARENA_SLAB_BITS",
    "TZ_ASSEMBLE_DEPTH",
    "TZ_ASSEMBLE_WORKERS",
    "TZ_BENCH_PLATFORM",
    "TZ_BENCH_PREFLIGHT_ATTEMPTS",
    "TZ_BENCH_PREFLIGHT_TIMEOUT",
    "TZ_BENCH_WARMUP_TIMEOUT_S",
    "TZ_BREAKER_BACKOFF_CAP_S",
    "TZ_BREAKER_BACKOFF_S",
    "TZ_BREAKER_THRESHOLD",
    "TZ_CKPT_INTERVAL_S",
    "TZ_CKPT_WAL_FSYNC",
    "TZ_CKPT_WAL_MAX_MB",
    "TZ_COMPILE_STORM_N",
    "TZ_COMPILE_STORM_WINDOW_S",
    "TZ_COVERAGE_AUDIT_S",
    "TZ_COVERAGE_INTERVAL_S",
    "TZ_COVERAGE_RING",
    "TZ_COVERAGE_STALL_EDGES",
    "TZ_COVERAGE_STALL_WINDOW_S",
    "TZ_DB_FSYNC",
    "TZ_FAULT_PLAN",
    "TZ_FLIGHT_DIR",
    "TZ_FLIGHT_RING",
    "TZ_FUZZER_LEASE_S",
    "TZ_HBM_CAPACITY_BYTES",
    "TZ_HBM_DRIFT_TOLERANCE_BYTES",
    "TZ_HBM_RECONCILE",
    "TZ_HINTS_BATCH",
    "TZ_HINTS_KMAX",
    "TZ_HINTS_LANE",
    "TZ_HINTS_VMAX",
    "TZ_HUB_DIGEST_BITS",
    "TZ_HUB_LEASE_S",
    "TZ_JAX_PLATFORM",
    "TZ_MANAGER_HTTP",
    "TZ_MANAGER_INPUTS_CAP",
    "TZ_MANAGER_SIGNAL_CAP",
    "TZ_MESH_COMPAT",
    "TZ_MESH_COV",
    "TZ_MESH_DEVICES",
    "TZ_MESH_WATCHDOG_DEADLINE_S",
    "TZ_MUTANT_PLANE_BITS",
    "TZ_MUTATE_BACKEND",
    "TZ_PIPELINE_BATCH",
    "TZ_PIPELINE_DISPATCH_DEPTH",
    "TZ_PIPELINE_FUSED",
    "TZ_RPC_BACKOFF_S",
    "TZ_RPC_REPLY_CACHE",
    "TZ_RPC_REPLY_CACHE_MB",
    "TZ_RPC_RETRIES",
    "TZ_SERVE_COMPOSE_INTERVAL_S",
    "TZ_SERVE_CREDIT_DECAY",
    "TZ_SERVE_CREDIT_FLOOR",
    "TZ_SERVE_LEASE_S",
    "TZ_SERVE_MAX_TENANTS",
    "TZ_SERVE_PLANE_BITS",
    "TZ_SERVE_PRICE",
    "TZ_SERVE_QUEUE_CAP",
    "TZ_SERVE_REBALANCE_S",
    "TZ_SERVE_STALL_WINDOW_S",
    "TZ_SIM_BACKEND",
    "TZ_SIM_EPOCH_BATCHES",
    "TZ_SIM_PLANE_BITS",
    "TZ_SIM_PRESCORE",
    "TZ_SLO_BREAKER_RATIO",
    "TZ_SLO_BURN",
    "TZ_SLO_DELIVERY_P99_S",
    "TZ_SLO_FAST_S",
    "TZ_SLO_INTERVAL_S",
    "TZ_SLO_MUTANT_RATE",
    "TZ_SLO_SLOW_S",
    "TZ_SLO_TRIAGE_P99_S",
    "TZ_SLO_UTIL_FLOOR",
    "TZ_TELEMETRY_SNAPSHOT",
    "TZ_TRACE_FILE",
    "TZ_TRACE_PROCESS",
    "TZ_TRACE_SAMPLE",
    "TZ_TRIAGE_BATCH",
    "TZ_TRIAGE_DEVICE",
    "TZ_TRIAGE_DISPATCH_DEPTH",
    "TZ_TRIAGE_FLUSH_S",
    "TZ_TRIAGE_MAX_EDGES",
    "TZ_WATCHDOG_COMPILE_S",
    "TZ_WATCHDOG_DEADLINE_S",
}

_warned: set[str] = set()
_warn_lock = threading.Lock()


def _env_num(name: str, default, conv):
    KNOWN_TZ_VARS.add(name)
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return conv(raw)
    except (ValueError, TypeError):
        log.logf(0, "ignoring malformed %s=%r (using default %r)",
                 name, raw, default)
        return default


def env_int(name: str, default: int) -> int:
    return _env_num(name, default, lambda s: int(s, 0))


def env_float(name: str, default: float) -> float:
    return _env_num(name, default, float)


def env_auto_int(name: str, default):
    """An int knob with an `auto` sentinel (TZ_ASSEMBLE_DEPTH=auto|N):
    returns None for auto/unset-with-None-default, an int for a
    numeric value, `default` (logged) for anything malformed."""
    KNOWN_TZ_VARS.add(name)
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    if raw.strip().lower() == "auto":
        return None
    try:
        return int(raw, 0)
    except (ValueError, TypeError):
        log.logf(0, "ignoring malformed %s=%r (using default %r)",
                 name, raw, default)
        return default


def env_choice(name: str, default: str, choices) -> str:
    """A string knob restricted to an allow-list
    (TZ_MUTATE_BACKEND=pallas|vmap|auto): case-insensitive match
    returns the canonical choice; anything else degrades to the
    default (logged), same discipline as the numeric knobs."""
    KNOWN_TZ_VARS.add(name)
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    v = raw.strip().lower()
    if v in choices:
        return v
    log.logf(0, "ignoring malformed %s=%r (using default %r; "
                "choices: %s)", name, raw, default, "|".join(choices))
    return default


def warn_unknown_tz_vars(environ=None) -> list[str]:
    """The typo guard: log (once per process per name) every TZ_*
    variable present in the environment that no knob parses — a
    misspelled kill switch must be loud, not silently inert.  Returns
    the names flagged by THIS call (tests), never raises."""
    env = os.environ if environ is None else environ
    flagged: list[str] = []
    with _warn_lock:
        for name in sorted(env):
            if not name.startswith("TZ_") or name in KNOWN_TZ_VARS \
                    or name in _warned:
                continue
            _warned.add(name)
            flagged.append(name)
    for name in flagged:
        log.logf(0, "unknown environment knob %s (typo? known TZ_* "
                    "knobs are catalogued in docs/health.md)", name)
    return flagged
