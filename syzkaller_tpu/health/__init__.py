"""Self-healing device runtime: watchdog, circuit breaker, and the
deterministic fault-injection harness.

The round-5 flagship rested on a single healthy measurement: a wedged
PJRT backend hung the pipeline worker forever and the one-shot rebuild
latch fired exactly once (BENCH_WEDGE_DIAGNOSIS.md, ADVICE.md r5).
This package makes every device failure path *detected*, *bounded*,
and *exercisable deterministically*:

  - faultinject: named seams (`device.launch`, `device.compile`,
    `device.triage`, `staging.h2d`, `rpc.send_frame`,
    `rpc.recv_frame`, `queue.put`)
    scripted by a TZ_FAULT_PLAN env plan — syzkaller's fail_nth
    discipline applied to the host side of the TPU engine,
  - watchdog: a heartbeat + deadline wrapper converting a wedged
    device call into a structured DeviceWedged instead of an eternal
    stall,
  - breaker: the closed → open → half-open → closed circuit breaker
    that replaces the ad-hoc errors_since_ok counter in
    DevicePipeline._worker, with transition counters for tests and
    the manager status page.

See docs/health.md for the state machine and the plan grammar.
"""

from syzkaller_tpu.health.breaker import BreakerCounters, CircuitBreaker
from syzkaller_tpu.health.envsafe import (
    KNOWN_TZ_VARS,
    env_auto_int,
    env_choice,
    env_float,
    env_int,
    warn_unknown_tz_vars,
)
from syzkaller_tpu.health.faultinject import (
    SEAMS,
    FaultInjected,
    FaultPlan,
    fault_point,
    install_plan,
    plan_from_env,
    reset_plan,
)
from syzkaller_tpu.health.watchdog import DeviceWedged, Watchdog

__all__ = [
    "BreakerCounters",
    "CircuitBreaker",
    "DeviceWedged",
    "FaultInjected",
    "FaultPlan",
    "KNOWN_TZ_VARS",
    "SEAMS",
    "Watchdog",
    "env_auto_int",
    "env_choice",
    "env_float",
    "env_int",
    "fault_point",
    "install_plan",
    "plan_from_env",
    "reset_plan",
    "warn_unknown_tz_vars",
]
