"""Watchdog: heartbeat + deadline around device dispatch and compile.

The round-5 wedge (BENCH_WEDGE_DIAGNOSIS.md) was a PJRT Client_Create
/ dispatch hanging on a dead relay: the worker thread blocked inside
the runtime forever, the pipeline produced nothing, and the fuzzer's
only signal was N drain timeouts later.  Python cannot cancel a
thread stuck in a C extension, but it CAN refuse to wait on one: the
watchdog runs each guarded call on a disposable daemon thread, waits
out the deadline, and converts a stall into a structured
DeviceWedged — the worker's failure handling (circuit breaker,
host-snapshot rebuild) then proceeds while the wedged call is
abandoned to finish (or not) in the background.

Deadlines come from the pipeline's env knobs (TZ_WATCHDOG_DEADLINE_S
for steady-state launches, TZ_WATCHDOG_COMPILE_S for the first call,
which carries the jit trace + tunnel compile).  A deadline of 0
disables the wrapper (direct call) for deployments that cannot spare
the thread-per-call overhead.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field

from syzkaller_tpu import telemetry

# Process-wide watchdog metrics (syzkaller_tpu/telemetry): folded into
# the same registry as the breaker transitions so /metrics and
# bench_watch's wedge diagnostics read one source of truth.
_M_CALLS = telemetry.counter(
    "tz_watchdog_calls_total", "device calls run under the watchdog")
_M_WEDGES = telemetry.counter(
    "tz_watchdog_wedges_total", "calls converted to DeviceWedged")
_M_LAST_WEDGE = telemetry.gauge(
    "tz_watchdog_last_wedge_ts",
    "wallclock timestamp of the most recent wedge (0 = never)")


class DeviceWedged(RuntimeError):
    """A guarded device call exceeded its watchdog deadline.  The
    call's thread is abandoned, not cancelled: `op` names the seam
    for triage and the breaker treats this like any device failure."""

    def __init__(self, op: str, deadline_s: float):
        super().__init__(
            f"device call {op!r} exceeded watchdog deadline "
            f"({deadline_s:.1f}s); treating the backend as wedged")
        self.op = op
        self.deadline_s = deadline_s


@dataclass
class WatchdogStats:
    calls: int = 0
    wedges: int = 0  # calls converted to DeviceWedged
    abandoned_live: int = 0  # wedged threads that never finished
    last_duration_s: float = 0.0
    last_op: str = ""
    last_wedge_at: float = 0.0  # wallclock; 0.0 = never wedged


class _Executor(threading.Thread):
    """A reusable guarded-call runner.  Spawning a fresh thread per
    guarded call cost ~0.5 ms — measurable once the triage engine
    started issuing a guarded call per batch — so the watchdog keeps
    an idle pool instead.  A wedged executor is `retired`: it finishes
    (or never finishes) its stuck call in the background and exits
    instead of pulling new work."""

    def __init__(self):
        super().__init__(daemon=True, name="watchdog-exec")
        self.tasks: queue.SimpleQueue = queue.SimpleQueue()
        self.retired = False
        self.start()

    def run(self) -> None:
        while True:
            fn, box, done = self.tasks.get()
            try:
                box["result"] = fn()
            except BaseException as e:  # delivered to the caller
                box["error"] = e
            finally:
                done.set()
            if self.retired:
                return


class Watchdog:
    """Deadline-guards blocking device calls; tracks a heartbeat.

    One watchdog per pipeline; call() may be invoked from any thread
    (the pipeline worker and the triage engine share one when
    co-resident — each concurrent call gets its own executor).
    """

    def __init__(self, deadline_s: float = 120.0,
                 compile_deadline_s: float = 600.0,
                 clock=time.monotonic):
        self.deadline_s = deadline_s
        self.compile_deadline_s = compile_deadline_s
        self._clock = clock
        self._lock = threading.Lock()
        self.stats = WatchdogStats()
        self._last_beat = clock()
        self._abandoned: list[threading.Thread] = []
        self._idle: list[_Executor] = []

    # -- heartbeat --------------------------------------------------------

    def beat(self) -> None:
        with self._lock:
            self._last_beat = self._clock()

    def since_last_beat(self) -> float:
        with self._lock:
            return self._clock() - self._last_beat

    # -- the guard --------------------------------------------------------

    def call(self, fn, op: str, deadline_s=None, compile: bool = False):
        """Run fn() under a deadline.  An explicit `deadline_s` is
        pinned for the call; with deadline_s=None the deadline is
        DYNAMIC — re-read from `self.deadline_s` (or
        `self.compile_deadline_s` when `compile`) on every wait tick,
        so tightening the knob applies to a call already in flight
        (an operator shortening deadlines on a wedging system — or a
        test doing the same — must not wait out the old deadline).
        Returns fn's result, re-raises its exception, or raises
        DeviceWedged when the deadline passes first."""
        def current() -> float:
            if deadline_s is not None:
                return deadline_s
            return self.compile_deadline_s if compile else self.deadline_s

        _M_CALLS.inc()
        with self._lock:
            self.stats.calls += 1
            self.stats.last_op = op
            # Reap abandoned threads that eventually came back.
            self._abandoned = [t for t in self._abandoned if t.is_alive()]
            self.stats.abandoned_live = len(self._abandoned)
        d0 = current()
        if not d0 or d0 <= 0:
            t0 = self._clock()
            try:
                return fn()
            finally:
                self._note_done(self._clock() - t0)
        box: dict = {}
        done = threading.Event()
        with self._lock:
            ex = self._idle.pop() if self._idle else None
        if ex is None:
            ex = _Executor()
        t0 = self._clock()
        ex.tasks.put((fn, box, done))
        while not done.wait(timeout=0.2):
            d = current()
            if d and d > 0 and self._clock() - t0 >= d:
                now = time.time()
                ex.retired = True  # still owns the stuck call
                # Poison task: if the call races to completion right
                # at the deadline, the executor is parked in get() —
                # the no-op lets it observe `retired` and exit.
                ex.tasks.put((lambda: None, {}, threading.Event()))
                with self._lock:
                    self.stats.wedges += 1
                    self.stats.last_wedge_at = now
                    self._abandoned.append(ex)
                    self.stats.abandoned_live = len(self._abandoned)
                _M_WEDGES.inc()
                _M_LAST_WEDGE.set(now)
                telemetry.record_event(
                    "watchdog.wedge",
                    f"{op} exceeded {d:.1f}s deadline")
                # Flight recorder: a wedge is THE incident the ring
                # exists for — dump the black box before anyone acts
                # on the failure (rate-limited, never raises).
                telemetry.FLIGHT.dump(
                    "device_wedged",
                    f"{op} exceeded {d:.1f}s watchdog deadline")
                raise DeviceWedged(op, d)
        with self._lock:
            self._idle.append(ex)
        self._note_done(self._clock() - t0)
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def _note_done(self, duration: float) -> None:
        with self._lock:
            self.stats.last_duration_s = duration
            self._last_beat = self._clock()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "calls": self.stats.calls,
                "wedges": self.stats.wedges,
                "abandoned_live": self.stats.abandoned_live,
                "last_wedge_at": round(self.stats.last_wedge_at, 3),
                "last_duration_s": round(self.stats.last_duration_s, 3),
                "since_last_beat_s": round(
                    self._clock() - self._last_beat, 3),
                "deadline_s": self.deadline_s,
                "compile_deadline_s": self.compile_deadline_s,
            }
