"""On-device simulated executor (ISSUE 15).

A batched device implementation of the sim-kernel semantics the C++
executor stub (executor/sim_kernel.h) and its Python twin
(ipc/sim.SimKernelModel) define — run directly on the packed delta
rows the mutator emits, BEFORE any byte crosses D2H:

  table.py    — lowers a serialized exec word stream / ExecTemplate
                into fixed-shape per-call argument tables
                (build_sim_table), plus the host parity oracle
                (sim_exec_host) the bit-exactness tests pin.
  kernel.py   — the vmap / Pallas grid-over-batch device kernel
                (sim_exec_batch) + the prescore plumbing
                (decode_rows, apply_deltas, predict_and_mark).
  prescore.py — per-pipeline speculation state: stacked tables,
                decaying speculation plane, breaker (SimPrescore).
  loadgen.py  — the VM-free serving-plane load generator
                (SimLoadGenerator) built on the same host model.

Wired into the fused drain by ops/pipeline (TZ_SIM_PRESCORE=1) and
benchable end-to-end via `python -m syzkaller_tpu.bench --sim`.
"""

from syzkaller_tpu.sim.kernel import (
    TABLE_FIELDS,
    resolve_sim_backend,
    sim_exec_batch,
)
from syzkaller_tpu.sim.loadgen import SimLoadGenerator
from syzkaller_tpu.sim.prescore import SimPrescore, resolve_sim_plane_bits
from syzkaller_tpu.sim.table import (
    SimTable,
    build_sim_table,
    build_sim_table_from_words,
    sim_exec_host,
)

__all__ = [
    "TABLE_FIELDS",
    "SimLoadGenerator",
    "SimPrescore",
    "SimTable",
    "build_sim_table",
    "build_sim_table_from_words",
    "resolve_sim_backend",
    "resolve_sim_plane_bits",
    "sim_exec_batch",
    "sim_exec_host",
]
