"""Speculative prescore state: tables, plane, breaker, accounting.

The fused drain's optional sim-exec stage (TZ_SIM_PRESCORE=1) needs
per-pipeline state that outlives any single batch:

  - the STACKED sim tables: every live exec template lowered
    (sim/table.py) into capacity-sized arrays the kernel gathers by
    template index.  Rebuilt incrementally — only slots whose
    template object changed re-lower — and re-uploaded whole when
    anything did (the upload is small next to one batch).
  - the SPECULATION PLANE: a 2^TZ_SIM_PLANE_BITS byte device bitmap
    of predicted-edge folds.  Decayed by FULL RESET every
    TZ_SIM_EPOCH_BATCHES batches: a mutant suppressed because its
    edges looked stale becomes admissible again next epoch, so the
    filter can delay true discovery by at most one epoch, never
    starve it (the re-admission guarantee the acceptance criteria
    pin).
  - its own CircuitBreaker: prescore failures demote to PASS-THROUGH
    (the plain fused step still ships every plane-novel mutant — zero
    lost mutants), symmetric with PipelineMutator's health latch.
    Probes re-enter via the shared TZ_BREAKER_* pacing knobs.

docs/perf.md "The speculation path" covers when the filter pays off;
docs/observability.md catalogues the tz_sim_* metrics and the
sim.demote / sim.repromote / sim.readmit / sim.suppress timeline
events emitted here.
"""

from __future__ import annotations

import numpy as np

from syzkaller_tpu import telemetry
from syzkaller_tpu.health import (
    CircuitBreaker,
    env_float,
    env_int,
)
from syzkaller_tpu.ipc.sim import SIM_MAX_ARGS
from syzkaller_tpu.sim.kernel import TABLE_FIELDS, resolve_sim_backend
from syzkaller_tpu.sim.table import build_sim_table
from syzkaller_tpu.utils import log

_M_SIM_BATCHES = telemetry.counter(
    "tz_sim_prescore_batches_total",
    "batches drained through the sim-exec prescore stage")
_M_SIM_SUPPRESSED = telemetry.counter(
    "tz_sim_suppressed_rows_total",
    "plane-novel rows the prescore predicted stale and held back")
_M_SIM_READMITS = telemetry.counter(
    "tz_sim_readmit_epochs_total",
    "speculation-plane decay epochs (suppressed rows re-admissible)")
_M_SIM_DEMOTIONS = telemetry.counter(
    "tz_sim_demotions_total",
    "prescore demotions to the pass-through drain")
_M_SIM_REPROMOTIONS = telemetry.counter(
    "tz_sim_repromotions_total",
    "prescore re-promotions after a successful probe")
_M_SIM_BACKEND = telemetry.gauge(
    "tz_sim_backend", "sim-exec backend in use (0 = vmap, 1 = pallas)")
_M_SIM_SUPPRESSION = telemetry.gauge(
    "tz_sim_suppression_rate",
    "suppressed fraction of the most recent prescored batch")


def resolve_sim_plane_bits() -> int:
    """TZ_SIM_PLANE_BITS with the same clamp discipline as the mutant
    plane (ops/signal.resolve_mutant_plane_bits): 2^20 = 1 MB default,
    bounded to [10, 28] so a typo cannot allocate a 4 GB plane."""
    bits = env_int("TZ_SIM_PLANE_BITS", 20)
    return min(28, max(10, bits))


class SimPrescore:
    """Per-pipeline prescore state (single worker-thread writer, same
    threading contract as the pipeline's own device attributes)."""

    def __init__(self, capacity: int, max_calls: int = 32,
                 backend: str | None = None, seed: int = 0):
        self.capacity = capacity
        self.max_calls = max_calls
        self.backend = resolve_sim_backend(backend)
        _M_SIM_BACKEND.set(1 if self.backend == "pallas" else 0)
        self.plane_bits = resolve_sim_plane_bits()
        self.epoch_batches = max(0, env_int("TZ_SIM_EPOCH_BATCHES", 64))
        self.breaker = CircuitBreaker(
            failure_threshold=max(1, env_int("TZ_BREAKER_THRESHOLD", 4)),
            backoff_initial=env_float("TZ_BREAKER_BACKOFF_S", 1.0),
            backoff_cap=env_float("TZ_BREAKER_BACKOFF_CAP_S", 60.0),
            seed=seed)
        C, A = max_calls, SIM_MAX_ARGS
        self._host = {
            "call_id": np.zeros((capacity, C), np.int32),
            "nargs": np.zeros((capacity, C), np.int32),
            "ret_idx": np.full((capacity, C), -1, np.int32),
            "amode": np.zeros((capacity, C, A), np.int32),
            "aslot": np.full((capacity, C, A), -1, np.int32),
            "aconst": np.zeros((capacity, C, A), np.uint64),
            "ameta": np.zeros((capacity, C, A), np.uint64),
            "aaux": np.zeros((capacity, C, A), np.uint64),
        }
        self._host_ncalls = np.zeros(capacity, np.int32)
        self._et_ids: list = [None] * capacity
        self._tables_dev = None
        self._plane = None
        # Residency ledger (ISSUE 17): the stacked sim tables and the
        # speculation plane are the sim path's long-lived device state.
        self._hbm_tables = telemetry.HBM.register(
            "sim", "tables", bound_to=self)
        self._hbm_plane = telemetry.HBM.register(
            "sim", "plane", bound_to=self)
        # Accounting (drained into proc stats / bench via snapshot()).
        self.batches = 0
        self.suppressed = 0
        self.epochs = 0
        self.demotions = 0
        self.repromotions = 0
        self._demoted = False
        self._epoch_evented = False

    # -- device state ------------------------------------------------------

    def device_tables(self, ets) -> dict:
        """The stacked device tables for this exec-template snapshot.
        Incremental: only changed slots re-lower; any change (or an
        invalidated device copy) re-uploads the stack."""
        import jax.numpy as jnp

        dirty = False
        for i, et in enumerate(ets[:self.capacity]):
            key = None if et is None else id(et)
            if self._et_ids[i] == key:
                continue  # unchanged slot (identity, _template_table)
            if et is None:
                self._et_ids[i] = None
                self._host_ncalls[i] = 0
                dirty = True
                continue
            t = build_sim_table(et, self.max_calls)
            for k in TABLE_FIELDS:
                self._host[k][i] = getattr(t, k)
            self._host_ncalls[i] = t.ncalls
            self._et_ids[i] = id(et)
            dirty = True
        if dirty or self._tables_dev is None:
            dev = {k: jnp.asarray(v) for k, v in self._host.items()}
            dev["ncalls"] = jnp.asarray(self._host_ncalls)
            self._tables_dev = dev
            self._hbm_tables.update(self._tables_dev)
        return self._tables_dev

    def ensure_plane(self):
        """The device speculation plane, zero-built lazily (and after
        each decay epoch / device-state invalidation)."""
        if self._plane is None:
            import jax.numpy as jnp

            self._plane = jnp.zeros(1 << self.plane_bits, jnp.uint8)
            self._hbm_plane.update(self._plane)
        return self._plane

    def invalidate_device_state(self) -> None:
        """Breaker re-entry / backend restart: device buffers are
        gone; host tables persist and re-upload on the next launch."""
        self._tables_dev = None
        self._et_ids = [None] * self.capacity
        self._plane = None
        self._hbm_tables.update(None)
        self._hbm_plane.update(None)

    # -- per-batch bookkeeping ---------------------------------------------

    def commit(self, plane) -> None:
        """A prescored batch dispatched: store the updated plane,
        advance the epoch clock (decay = full plane reset, making
        every previously-suppressed fold admissible again), and let
        the breaker see the success."""
        self._plane = plane
        self._hbm_plane.update(plane)
        self.batches += 1
        if self.epoch_batches and self.batches % self.epoch_batches == 0:
            self._plane = None
            self._hbm_plane.update(None)
            self.epochs += 1
            self._epoch_evented = False
            _M_SIM_READMITS.inc()
            telemetry.record_event(
                "sim.readmit",
                f"speculation plane decayed (epoch {self.epochs})")
        self.breaker.record_success()
        if self._demoted:
            self._demoted = False
            self.repromotions += 1
            _M_SIM_REPROMOTIONS.inc()
            telemetry.record_event("sim.repromote",
                                   "prescore answering again")
            log.logf(0, "sim prescore re-promoted (device answering)")

    def note_batch(self, n_suppressed: int, batch_size: int) -> None:
        """Drain-side accounting for one prescored batch (called with
        the synced suppression count)."""
        self.suppressed += n_suppressed
        _M_SIM_BATCHES.inc()
        _M_SIM_SUPPRESSED.inc(n_suppressed)
        _M_SIM_SUPPRESSION.set(n_suppressed / max(1, batch_size))
        if n_suppressed and not self._epoch_evented:
            # One timeline entry per epoch, not per batch — the
            # timeline is for transitions, the counters carry volume.
            self._epoch_evented = True
            telemetry.record_event(
                "sim.suppress",
                f"{n_suppressed} rows held back this batch")

    def note_failure(self, exc: BaseException) -> None:
        """A prescore failure (fault seam, table lowering, dispatch):
        breaker bookkeeping + demotion to pass-through.  The caller
        falls back to the plain fused step, so no mutant is lost."""
        self.breaker.record_failure()
        if not self._demoted:
            self._demoted = True
            self.demotions += 1
            _M_SIM_DEMOTIONS.inc()
            telemetry.record_event("sim.demote", str(exc)[:120])
            log.logf(0, "sim prescore DEMOTED to pass-through: %s",
                     str(exc)[:200])

    def demoted(self) -> bool:
        return self._demoted

    def snapshot(self) -> dict:
        return {
            "backend": self.backend,
            "plane_bits": self.plane_bits,
            "epoch_batches": self.epoch_batches,
            "demoted": self._demoted,
            "batches": self.batches,
            "suppressed": self.suppressed,
            "epochs": self.epochs,
            "demotions": self.demotions,
            "repromotions": self.repromotions,
            "breaker": self.breaker.snapshot(),
        }
