"""VM-free load generator for serving/control-plane stress tests.

The serving plane's tests (and any control-plane soak) need a drain
that produces a REALISTIC multi-tenant verdict mix — novel rows,
stale repeats, crashy programs, EBADF returns — without spawning a
single executor subprocess or touching a device.  This module drives
the sim-kernel host model (ipc/sim.SimKernelModel, the same semantics
the device prescore kernel mirrors bit-exactly) over deterministically
generated programs and emits composer-compatible batches:

    gen = SimLoadGenerator(spec, seed=7)
    composer = BatchComposer(broker, planes, drain_fn=gen.drain, ...)

`drain(n)` returns `(rows, payloads)` in exactly the shape
serve/composer.BatchComposer expects from the device drain: `rows`
uint8[n, spec.row_bytes] packed delta rows (the novelty-verdict
input — a repeated program re-emits byte-identical rows, so the
tenant planes see genuine staleness, not synthetic flags), `payloads`
a same-length list of bytes (the program's (call_id, args) words).

Everything is derived from splitmix64 chains on (seed, program
index): no RNG module, no wall clock, no global state — two
generators with the same seed produce the same byte stream, which is
what the serving tests pin.  docs/perf.md "The speculation path"
covers where this slots into the stress story.
"""

from __future__ import annotations

import numpy as np

from syzkaller_tpu.ipc.sim import (
    MASK64,
    SimKernelModel,
    arg_magic,
    call_hash,
    crash_magics,
    is_crashy,
    is_lockless,
    splitmix64,
)
from syzkaller_tpu.ops.delta import OP_MUTATE, DeltaSpec

#: How many distinct (call_id) values the generator draws from.  Small
#: on purpose: entry edges repeat across programs, so the verdict mix
#: has genuine overlap instead of every row being trivially novel.
CALL_ID_SPACE = 24

#: Probability denominators (1-in-N per splitmix64 draw).
_P_MAGIC = 4       # arg hits its magic comparand
_P_CRASH_ARM = 6   # crashy call gets its first crash comparand
_P_CRASH_FULL = 3  # ... and the second (given armed)
_P_HANDLE = 3      # arg reuses a live handle


class SimLoadGenerator:
    """Deterministic composer-compatible drain over the sim kernel."""

    def __init__(self, spec: DeltaSpec | None = None, seed: int = 1,
                 max_calls: int = 4, repeat_every: int = 4,
                 pid: int = 0):
        self.spec = spec if spec is not None else DeltaSpec()
        self.seed = int(seed) & MASK64
        self.max_calls = max(1, max_calls)
        #: Every `repeat_every`-th row re-emits a recently generated
        #: program byte-for-byte (0 disables repeats entirely).
        self.repeat_every = max(0, repeat_every)
        self.pid = pid
        self._i = 0  # program counter across drain() calls
        self._recent: list[tuple[np.ndarray, bytes]] = []
        self.stats = {
            "programs": 0, "calls": 0, "repeats": 0, "crashes": 0,
            "ebadf": 0, "magic_hits": 0, "handle_hits": 0,
            "lockless_calls": 0,
        }

    # -- deterministic draws ----------------------------------------------

    def _chain(self, i: int):
        """A per-program splitmix64 draw stream: same (seed, i) ->
        same program, independent of drain() batching."""
        x = splitmix64(self.seed ^ ((i * 0x9E3779B97F4A7C15) & MASK64))

        def nxt() -> int:
            nonlocal x
            x = splitmix64(x)
            return x
        return nxt

    # -- program generation ------------------------------------------------

    def _program(self, i: int) -> list[tuple[int, list[int]]]:
        """Program i: a short call sequence with probability-weighted
        magic / crash-comparand / handle-reuse hits, so executing it
        through the sim kernel yields the full verdict zoo."""
        nxt = self._chain(i)
        ncalls = 1 + nxt() % self.max_calls
        handles: list[int] = []
        prog: list[tuple[int, list[int]]] = []
        # A shadow of the model's ctor rule, just to know which handle
        # values exist for reuse draws (exactness does not matter — a
        # stale guess simply misses, like a real fuzzer's would).
        n_handles = 0
        for _c in range(ncalls):
            call_id = nxt() % CALL_ID_SPACE
            h = call_hash(call_id)
            nargs = nxt() % 5
            args: list[int] = []
            for j in range(nargs):
                if nxt() % _P_MAGIC == 0:
                    args.append(arg_magic(call_id, j))
                elif handles and nxt() % _P_HANDLE == 0:
                    args.append(handles[nxt() % len(handles)])
                else:
                    args.append(nxt() % 0x10000)
            if is_crashy(call_id) and nargs >= 2 \
                    and nxt() % _P_CRASH_ARM == 0:
                c0, c1 = crash_magics(call_id)
                args[0] = c0
                if nxt() % _P_CRASH_FULL == 0:
                    args[1] = c1
                    prog.append((call_id, args))
                    break  # a full crash ends the program
            prog.append((call_id, args))
            if (h & 3) == 1 and not is_lockless(call_id):
                handles.append(
                    0x1000 + ((n_handles * 4 + self.pid) % 0xFFFFF))
                n_handles += 1
        return prog

    def _emit(self, i: int) -> tuple[np.ndarray, bytes]:
        """Execute program i through the host sim kernel (for the
        verdict-mix stats) and pack one delta row + payload."""
        prog = self._program(i)
        model = SimKernelModel(pid=self.pid)
        st = self.stats
        st["programs"] += 1
        for call_id, args in prog:
            st["calls"] += 1
            if is_lockless(call_id):
                st["lockless_calls"] += 1
            res = model.exec(call_id, args)
            if res.crashed:
                st["crashes"] += 1
                break
            if res.errno == 9:
                st["ebadf"] += 1
            st["magic_hits"] += sum(
                1 for j, a in enumerate(args)
                if a == arg_magic(call_id, j))
        st["handle_hits"] += len(model.handles)
        # The payload is the program's words; the row embeds a digest
        # of those words in its value slots, so byte-identical rows
        # <=> identical programs (the tenant-plane novelty input).
        words: list[int] = []
        for call_id, args in prog:
            words.append((len(args) << 32) | call_id)
            words.extend(a & MASK64 for a in args)
        payload = np.asarray(words, np.uint64).tobytes()
        row = np.zeros(self.spec.row_bytes, np.uint8)
        row[3] = OP_MUTATE
        row[4:8] = np.frombuffer(
            np.int32(i & 0x3FF).tobytes(), np.uint8)
        row[8:16] = 0xFF  # alive_bits: all calls live
        digest = np.zeros(self.spec.K, np.uint64)
        acc = splitmix64(self.seed ^ i)
        for w in words[:self.spec.K]:
            acc = splitmix64(acc ^ w)
        for k in range(self.spec.K):
            acc = splitmix64(acc)
            digest[k] = acc
        o_vals = self.spec.o_vals
        row[o_vals:o_vals + 8 * self.spec.K] = np.frombuffer(
            digest.tobytes(), np.uint8)
        return row, payload

    # -- the composer-facing drain -----------------------------------------

    def drain(self, n: int) -> tuple[np.ndarray, list[bytes]]:
        """Produce n composer rows: mostly fresh programs, with every
        `repeat_every`-th row a byte-identical replay of a recent one
        (a genuinely stale row for the tenant planes)."""
        rows = np.zeros((n, self.spec.row_bytes), np.uint8)
        payloads: list[bytes] = []
        for j in range(n):
            self._i += 1
            if (self.repeat_every and self._recent
                    and self._i % self.repeat_every == 0):
                k = splitmix64(self.seed ^ self._i) % len(self._recent)
                row, payload = self._recent[k]
                self.stats["repeats"] += 1
            else:
                row, payload = self._emit(self._i)
                self._recent.append((row, payload))
                if len(self._recent) > 64:
                    self._recent.pop(0)
            rows[j] = row
            payloads.append(payload)
        return rows, payloads

    def verdict_mix(self) -> dict:
        """Fractions for tests/docs: what the generated load looked
        like (crash / EBADF / lockless / repeat rates)."""
        st = self.stats
        progs = max(1, st["programs"])
        calls = max(1, st["calls"])
        emitted = max(1, st["programs"] + st["repeats"])
        return {
            "crash_frac": st["crashes"] / progs,
            "ebadf_frac": st["ebadf"] / calls,
            "lockless_frac": st["lockless_calls"] / calls,
            "repeat_frac": st["repeats"] / emitted,
        }
