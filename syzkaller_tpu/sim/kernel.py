"""Batched device sim-exec: executor/sim_kernel.h as a JAX kernel.

One grid cell (Pallas) or vmap lane executes one mutant's lowered
SimTable program (sim/table.py) end to end on device: resolve every
call arg (slot gather, proc encode, copyout-chain result refs, the
executor's pid-stride + big-endian const transform), run the
simulated kernel's deterministic edge map (splitmix64 hash chain,
value buckets, magic comparands, handle set, combo edges, two-stage
crash, lockless race families), and emit the fixed-slot edge/validity
layout ipc/sim.SimKernelModel defines.  The host model is the parity
oracle: for identical inputs every output array here must match
sim_exec_host bit for bit.

Like the mutation core (ops/pallas_mutate), the per-call loop is a
lax.fori_loop whose carry is the simulated kernel state (handle set,
copyout window, crash latch), arg handling is vectorized across the
8-arg window, and the Pallas path reuses _grid_apply so TPU gets a
grid-over-batch kernel while every other backend runs the bit-exact
vmap twin (`TZ_SIM_BACKEND` override, auto elsewhere).
"""

from __future__ import annotations

import numpy as np

from syzkaller_tpu.health.envsafe import env_choice
from syzkaller_tpu.ipc.sim import (
    SIM_EDGE_SLOTS,
    SIM_MAX_ARGS,
    SIM_SLOT_BUCKET0,
    SIM_SLOT_COMBO_HANDLES,
    SIM_SLOT_COMBO_MIXED,
    SIM_SLOT_CRASH_ARM,
    SIM_SLOT_ENTRY,
    SIM_SLOT_HANDLE0,
    SIM_SLOT_MAGIC0,
)
from syzkaller_tpu.sim.table import (
    MODE_CONST,
    MODE_PROC,
    MODE_RESULT,
    MODE_SLOT,
    SIM_MAX_COPYOUT,
    STATUS_CRASHED,
    STATUS_RAN,
)

#: Stacked-table array fields, in the argument order the kernel takes.
TABLE_FIELDS = ("call_id", "nargs", "ret_idx", "amode", "aslot",
                "aconst", "ameta", "aaux")


def resolve_sim_backend(explicit: str | None = None) -> str:
    """Same discipline as ops/pallas_mutate.resolve_mutate_backend:
    explicit argument wins, then TZ_SIM_BACKEND=pallas|vmap|auto,
    then Pallas only on TPU."""
    if explicit in ("pallas", "vmap"):
        return explicit
    choice = env_choice("TZ_SIM_BACKEND", "auto",
                        ("auto", "pallas", "vmap"))
    if choice in ("pallas", "vmap"):
        return choice
    import jax

    return "pallas" if jax.default_backend() == "tpu" else "vmap"


def _u64(v):
    return np.uint64(v)


def _sm64(x):
    """splitmix64 on uint64 arrays (executor/sim_kernel.h)."""
    import jax.numpy as jnp

    x = x + _u64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> _u64(30))) * _u64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> _u64(27))) * _u64(0x94D049BB133111EB)
    return x ^ (x >> _u64(31))


def _pc(seed):
    """emit(): the low 32 bits of splitmix64(seed)."""
    return (_sm64(seed) & _u64(0xFFFFFFFF)).astype("uint32")


def _bswap64(v):
    import jax.numpy as jnp

    r = jnp.zeros_like(v)
    for k in range(8):
        r = r | (((v >> _u64(8 * k)) & _u64(0xFF)) << _u64(8 * (7 - k)))
    return r


def _value_bucket(v):
    """sim_kernel.h value_bucket as a branch-free binary search:
    floor(log2(v)) (0 for v in {0,1}) in the high bits, the low
    nibble verbatim."""
    import jax.numpy as jnp

    x = v
    r = jnp.zeros_like(v)
    for sh in (32, 16, 8, 4, 2, 1):
        m = x >> _u64(sh)
        t = m > _u64(0)
        x = jnp.where(t, m, x)
        r = r + jnp.where(t, _u64(sh), _u64(0))
    return (r << _u64(4)) | (v & _u64(0xF))


def _transform_const(raw, meta):
    """executor read_arg const transform minus the pid stride (the
    kernel runs as pid 0, the prescore contract): big-endian args are
    bswap64'd then shifted down to their byte size."""
    import jax.numpy as jnp

    be = ((meta >> _u64(8)) & _u64(1)) != _u64(0)
    sz = jnp.clip(meta & _u64(0xFF), _u64(1), _u64(8))
    swapped = _bswap64(raw) >> (_u64(64) - _u64(8) * sz)
    return jnp.where(be, swapped, raw)


def make_sim_exec_one(C: int, S: int, pid: int = 0):
    """Build the per-mutant sim-exec function.

    one(call_id i32[C], nargs i32[C], ret_idx i32[C],
        amode i32[C,A], aslot i32[C,A], aconst u64[C,A],
        ameta u64[C,A], aaux u64[C,A],
        ncalls i32, alive_bits u64, vals u64[S])
      -> (edges u32[C,E], valid bool[C,E], ret u64[C],
          errno i32[C], status i32[C])

    Pure jnp — composable under vmap, _grid_apply and the fused
    pipeline step."""
    import jax
    import jax.numpy as jnp

    A = SIM_MAX_ARGS
    E = SIM_EDGE_SLOTS
    CO = SIM_MAX_COPYOUT
    pid_u = _u64(pid)

    def one(call_id, nargs, ret_idx, amode, aslot, aconst, ameta,
            aaux, ncalls, alive_bits, vals):
        edges0 = jnp.zeros((C, E), dtype=jnp.uint32)
        valid0 = jnp.zeros((C, E), dtype=bool)
        ret0 = jnp.zeros(C, dtype=jnp.uint64)
        errno0 = jnp.zeros(C, dtype=jnp.int32)
        status0 = jnp.zeros(C, dtype=jnp.int32)
        handles0 = jnp.zeros(C, dtype=jnp.uint64)
        covals0 = jnp.zeros(CO, dtype=jnp.uint64)
        codone0 = jnp.zeros(CO, dtype=bool)

        def body(c, carry):
            (edges, valid, ret, errno, status, handles, nh, covals,
             codone, crashed) = carry
            run = (c < ncalls) \
                & (((alive_bits >> c.astype(jnp.uint64)) & _u64(1))
                   != _u64(0)) \
                & ~crashed
            na = nargs[c]
            h = _sm64(call_id[c].astype(jnp.uint64)
                      * _u64(0x10001) + _u64(1))

            # ---- resolve the 8-arg window (vectorized over A) ----
            mode = amode[c]
            slot = aslot[c]
            cst = aconst[c]
            meta = ameta[c]
            aux = aaux[c]
            sv = vals[jnp.clip(slot, 0, S - 1)]
            is_def = sv == _u64(0xFFFFFFFFFFFFFFFF)
            raw = jnp.where(
                mode == MODE_SLOT, sv,
                jnp.where(mode == MODE_PROC,
                          jnp.where(is_def, _u64(0), cst + sv),
                          cst))
            m = jnp.where((mode == MODE_PROC) & is_def, aux, meta)
            # pid stride (meta>>32 per pid) — static pid, u64 wrap.
            strided = raw + (m >> _u64(32)) * pid_u
            direct = _transform_const(strided, m)
            # MODE_RESULT: covals chain, untransformed.
            ridx = jnp.clip(slot, 0, CO - 1)
            rdone = (slot >= 0) & codone[ridx]
            rv = jnp.where(rdone, covals[ridx], cst)
            div = meta
            rv = jnp.where(div != _u64(0),
                           rv // jnp.maximum(div, _u64(1)), rv)
            rv = rv + aux
            arg = jnp.where(mode == MODE_RESULT, rv,
                            jnp.where(mode == MODE_CONST,
                                      direct,
                                      jnp.where((mode == MODE_SLOT)
                                                | (mode == MODE_PROC),
                                                direct, _u64(0))))
            argmask = jnp.arange(A) < na

            # ---- the simulated kernel's edge map ----
            iu = jnp.arange(A, dtype=jnp.uint64)
            entry_pc = _pc(h)
            bucket_pc = _pc(h ^ _sm64((iu << _u64(32))
                                      | _value_bucket(arg)))
            magic = _sm64(h + _u64(0x1111) * (iu + _u64(1))) \
                & _u64(0xFFFFFFFF)
            magic_hit = (arg == magic) & argmask
            magic_pc0 = _pc(h ^ _sm64(_u64(0xABCD0000) + iu))
            magic_pc1 = _pc(h ^ _sm64(_u64(0xABCD1000) + iu
                                      + (magic & _u64(0xFF))))
            handle_pc = _pc(h ^ _sm64(_u64(0xFEED0000) + iu))
            # Membership is checked BEFORE this call's own insert
            # (sim_kernel.h: handle test precedes the ctor).
            known = (jnp.arange(C) < nh)[None, :]
            handle_hit = ((arg[:, None] == handles[None, :]) & known) \
                .any(axis=1) & argmask
            magic_hits = magic_hit.sum()
            handle_hits = handle_hit.sum()

            rtag = h & _u64(31)
            lockless = (rtag == _u64(5)) | (rtag == _u64(9))
            crashy = ((h & _u64(7)) == _u64(3)) & (na >= 2) & ~lockless
            c0 = _sm64(h ^ _u64(0xC0DE0000)) & _u64(0xFFFFFFFF)
            c1 = _sm64(h ^ _u64(0xC0DE0001)) & _u64(0xFFFFFFFF)
            armed = crashy & (arg[0] == c0)
            full_crash = armed & (arg[1] == c1)

            pcs = jnp.zeros(E, dtype=jnp.uint32)
            ok = jnp.zeros(E, dtype=bool)
            pcs = pcs.at[SIM_SLOT_ENTRY].set(entry_pc)
            ok = ok.at[SIM_SLOT_ENTRY].set(True)
            sl = jnp.arange(A)
            pcs = pcs.at[SIM_SLOT_BUCKET0 + sl].set(bucket_pc)
            ok = ok.at[SIM_SLOT_BUCKET0 + sl].set(argmask & ~lockless)
            pair = jnp.stack([magic_pc0, magic_pc1], axis=1).reshape(-1)
            pcs = pcs.at[SIM_SLOT_MAGIC0 + jnp.arange(2 * A)].set(pair)
            mok = jnp.stack([magic_hit, magic_hit], axis=1).reshape(-1)
            ok = ok.at[SIM_SLOT_MAGIC0 + jnp.arange(2 * A)] \
                .set(mok & ~lockless)
            pcs = pcs.at[SIM_SLOT_HANDLE0 + sl].set(handle_pc)
            ok = ok.at[SIM_SLOT_HANDLE0 + sl] \
                .set(handle_hit & ~lockless)
            pcs = pcs.at[SIM_SLOT_COMBO_HANDLES].set(_pc(h ^ _u64(0x10)))
            ok = ok.at[SIM_SLOT_COMBO_HANDLES] \
                .set((handle_hits >= 2) & ~lockless)
            pcs = pcs.at[SIM_SLOT_COMBO_MIXED].set(_pc(h ^ _u64(0x11)))
            ok = ok.at[SIM_SLOT_COMBO_MIXED] \
                .set((handle_hits >= 1) & (magic_hits >= 1) & ~lockless)
            pcs = pcs.at[SIM_SLOT_CRASH_ARM].set(_pc(h ^ _u64(0xDEAD0)))
            ok = ok.at[SIM_SLOT_CRASH_ARM].set(armed)
            # A full crash _exits before copyout: nothing survives.
            ok = ok & run & ~full_crash

            # ---- ctor / errno / copyout state ----
            is_ctor = ((h & _u64(3)) == _u64(1)) & ~lockless \
                & ~full_crash
            new_handle = _u64(0x1000) \
                + (nh.astype(jnp.uint64) * _u64(4) + pid_u) \
                % _u64(0xFFFFF)
            hidx = jnp.where(run & is_ctor, nh, C)
            handles = handles.at[hidx].set(new_handle, mode="drop")
            nh = nh + (run & is_ctor).astype(jnp.int32)
            wants = ((h & _u64(3)) == _u64(2)) & (na > 0) & ~lockless
            errno_c = jnp.where(wants & (handle_hits == 0) & ~is_ctor
                                & ~full_crash,
                                jnp.int32(9), jnp.int32(0))
            ret_c = jnp.where(is_ctor, new_handle, _u64(0))
            status_c = jnp.where(
                full_crash, jnp.int32(STATUS_CRASHED),
                jnp.int32(STATUS_RAN))

            do_co = run & ~full_crash & (ret_idx[c] >= 0) \
                & (errno_c == 0)
            cidx = jnp.where(do_co, ret_idx[c], CO)
            covals = covals.at[cidx].set(ret_c, mode="drop")
            codone = codone.at[cidx].set(True, mode="drop")

            edges = edges.at[c].set(jnp.where(run, pcs, 0))
            valid = valid.at[c].set(ok)
            ret = ret.at[c].set(jnp.where(run & ~full_crash,
                                          ret_c, _u64(0)))
            errno = errno.at[c].set(jnp.where(run & ~full_crash,
                                              errno_c, 0))
            status = status.at[c].set(
                jnp.where(run, status_c, jnp.int32(0)))
            crashed = crashed | (run & full_crash)
            return (edges, valid, ret, errno, status, handles, nh,
                    covals, codone, crashed)

        out = jax.lax.fori_loop(
            0, C, body,
            (edges0, valid0, ret0, errno0, status0, handles0,
             jnp.int32(0), covals0, codone0, jnp.bool_(False)))
        return out[0], out[1], out[2], out[3], out[4]

    return one


def sim_exec_batch(table_rows: dict, ncalls, alive_bits, vals,
                   backend: str, interpret: bool = True,
                   pid: int = 0):
    """Run the sim-exec kernel over a batch.

    table_rows: dict of TABLE_FIELDS arrays, each (B, C[, A]) — the
    stacked tables already gathered by template index.  ncalls (B,)
    i32, alive_bits (B,) u64, vals (B, S) u64.  backend "pallas"
    routes through ops/pallas_mutate._grid_apply (grid-over-batch),
    anything else through vmap.  Traceable: call inside a jit."""
    import jax
    import jax.numpy as jnp

    C = table_rows["call_id"].shape[1]
    S = vals.shape[1]
    one = make_sim_exec_one(C, S, pid=pid)
    row_arrays = [table_rows[k] for k in TABLE_FIELDS] \
        + [jnp.asarray(ncalls, dtype=jnp.int32),
           jnp.asarray(alive_bits, dtype=jnp.uint64), vals]
    if backend == "pallas":
        from syzkaller_tpu.ops.pallas_mutate import _grid_apply

        E = SIM_EDGE_SLOTS
        return tuple(_grid_apply(
            one, row_arrays, [],
            out_shapes=[(C, E), (C, E), (C,), (C,), (C,)],
            out_dtypes=[jnp.uint32, jnp.bool_, jnp.uint64,
                        jnp.int32, jnp.int32],
            interpret=interpret))
    return jax.vmap(one)(*row_arrays)


def decode_rows(rows, K: int):
    """Pull the sim-relevant fields out of packed delta rows
    (ops/delta row layout): op u8 (B,), template_idx i32 (B,),
    alive_bits u64 (B,), val_idx i32 (B,K), vals u64 (B,K).
    Traceable; bitcasts match the packer's on-device row writes."""
    import jax
    import jax.numpy as jnp

    op = rows[:, 3]
    tidx = jax.lax.bitcast_convert_type(rows[:, 4:8], jnp.int32)
    alive = jax.lax.bitcast_convert_type(rows[:, 8:16], jnp.uint64)
    B = rows.shape[0]
    o = 28  # delta.HDR_BYTES == o_val_idx
    vi16 = jax.lax.bitcast_convert_type(
        rows[:, o:o + 2 * K].reshape(B, K, 2), jnp.int16)
    val_idx = vi16.astype(jnp.int32)
    vals = jax.lax.bitcast_convert_type(
        rows[:, o + 2 * K:o + 10 * K].reshape(B, K, 8), jnp.uint64)
    return op, tidx, alive, val_idx, vals


def apply_deltas(corpus_val, tidx, val_idx, vals_j):
    """Materialize each mutant's full slot vector: gather the base
    template's slots, scatter the K changed (slot, value) pairs
    (negative slots dropped).  Returns (B, S) u64."""
    import jax.numpy as jnp

    cap = corpus_val.shape[0]
    S = corpus_val.shape[1]
    B = tidx.shape[0]
    ti = jnp.clip(tidx, 0, cap - 1)
    base = corpus_val[ti]
    sidx = jnp.where(val_idx >= 0, val_idx, S)
    return base.at[jnp.arange(B)[:, None], sidx] \
        .set(vals_j, mode="drop")


def fold_edge_idx(edges, bits: int):
    """Edge PC -> speculation-plane index, the same xor-fold as
    ops/signal.fold_mutant_idx so plane statistics are comparable."""
    mask = np.uint32((1 << bits) - 1)
    return ((edges ^ (edges >> np.uint32(bits))) & mask) \
        .astype("int32")


def predict_and_mark(edges, valid, plane, bits: int):
    """The prescore: a mutant is predicted-novel iff ANY of its valid
    sim edges folds to an unmarked plane cell.  Marks every valid
    edge (predicted-novel or not) so repeats are suppressed next
    batch.  Returns (pred bool (B,), plane')."""
    import jax.numpy as jnp

    size = 1 << bits
    idx = fold_edge_idx(edges, bits)
    fresh = (plane[idx] == 0) & valid
    pred = fresh.reshape(fresh.shape[0], -1).any(axis=1)
    mark = jnp.where(valid, idx, size)
    plane = plane.at[mark.reshape(-1)].set(jnp.uint8(1), mode="drop")
    return pred, plane
