"""Sim-exec tables: exec wire streams lowered to fixed-shape arrays.

The on-device simulated executor cannot walk the variable-length exec
word stream (data regions, csum chunks and per-call arg counts make
the layout data-dependent), so this module walks it ONCE per template
on the host and lowers every call-position argument to a fixed
(mode, slot, const, meta, aux) quintuple:

  MODE_ZERO    data/csum at a call position — the executor's read_arg
               yields 0 for these
  MODE_CONST   static const (incl. pointer args and result args with
               no referenced result): value/meta straight from the
               template words, subject to the executor's pid-stride +
               big-endian transform
  MODE_SLOT    a device-mutable value slot (INT/FLAGS/LEN): the value
               comes from the mutant's slot vector, the meta word is
               the template's static meta
  MODE_RESULT  a resolved result reference: covals[idx] if the
               producing call copied out, else the type default, then
               op_div / op_add
  MODE_PROC    a device-mutable PROC slot: the 0xFF..F default
               serializes as 0 with the default meta, concrete values
               as aux0+v with the concrete meta (ops/emit.assemble)

The same walk with no template attached (build_sim_table_from_words)
lowers ANY assembled exec stream — every arg becomes static — which
is how parity tests check an assembled mutant byte stream against the
device kernel, and how the VM-free load generator scores programs.

sim_exec_host() is the bit-exactness oracle: it runs a lowered table
through ipc/sim.SimKernelModel with the executor's sequencing rules
(skip dead calls, stop at a full crash, persist a ret-backed copyout
only when errno == 0) and the SAME bounded copyout window the device
kernel uses.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from syzkaller_tpu.ipc.sim import (
    SIM_EDGE_SLOTS,
    SIM_MAX_ARGS,
    SimKernelModel,
)
from syzkaller_tpu.models.encodingexec import (
    EXEC_ARG_CONST,
    EXEC_ARG_CSUM,
    EXEC_ARG_DATA,
    EXEC_ARG_RESULT,
    EXEC_INSTR_COPYIN,
    EXEC_INSTR_COPYOUT,
    EXEC_INSTR_EOF,
    EXEC_NO_COPYOUT,
)

MASK64 = (1 << 64) - 1

MODE_ZERO = 0
MODE_CONST = 1
MODE_SLOT = 2
MODE_RESULT = 3
MODE_PROC = 4

#: Device copyout window.  The executor's table is MAX_COPYOUT=256,
#: but ret-backed indices (the only ones the sim models — memory-
#: backed copyouts read guest memory the sim does not have) are
#: assigned first-come per call, so a small dense window covers real
#: templates.  An index outside the window resolves as never-done
#: (type default) on BOTH the device kernel and the host oracle, so
#: parity holds by construction.
SIM_MAX_COPYOUT = 64

#: Default call capacity for standalone tables (alive_bits is u64, so
#: 64 is the hard ceiling; the prescore stacker sizes its own).
SIM_MAX_CALLS = 32

#: Sim-call run status (the device kernel's status output).
STATUS_SKIPPED = 0
STATUS_RAN = 1
STATUS_CRASHED = 2


@dataclass
class SimTable:
    """One template's lowered sim-exec program (host arrays)."""

    ncalls: int
    call_id: np.ndarray  # int32[C]
    nargs: np.ndarray  # int32[C]
    ret_idx: np.ndarray  # int32[C], -1 = no modelled copyout
    amode: np.ndarray  # int32[C, A]
    aslot: np.ndarray  # int32[C, A]  slot / copyout idx, mode-dependent
    aconst: np.ndarray  # uint64[C, A]  const val / proc aux0 / default
    ameta: np.ndarray  # uint64[C, A]  meta word / op_div / concrete meta
    aaux: np.ndarray  # uint64[C, A]  op_add / default proc meta


def _skip_arg(words: np.ndarray, p: int) -> int:
    """Advance p past one serialized arg (models/encodingexec layout)."""
    kind = int(words[p])
    if kind == EXEC_ARG_CONST:
        return p + 3
    if kind == EXEC_ARG_RESULT:
        return p + 6
    if kind == EXEC_ARG_DATA:
        lenword = int(words[p + 1])
        region = max(lenword & 0xFFFFFFFF, lenword >> 32)
        padded = region + (-region) % 8
        return p + 2 + padded // 8
    if kind == EXEC_ARG_CSUM:
        nchunks = int(words[p + 3])
        return p + 4 + 3 * nchunks
    raise ValueError(f"unknown exec arg kind {kind} at word {p}")


def _walk_calls(words: np.ndarray):
    """Yield (call_word_pos,) for every call instruction, skipping
    copyin/csum/copyout instructions — the same dispatch the executor's
    run loop performs."""
    p = 0
    while True:
        w = int(words[p])
        if w == EXEC_INSTR_EOF:
            return
        if w == EXEC_INSTR_COPYIN:
            p = _skip_arg(words, p + 2)
        elif w == EXEC_INSTR_COPYOUT:
            p += 4
        else:
            yield p
            p += 2  # call word + copyout word
            nargs = int(words[p])
            p += 1
            for _ in range(nargs):
                p = _skip_arg(words, p)


def _lower(words: np.ndarray, word2slot: dict, et,
           max_calls: int) -> SimTable:
    call_id = np.zeros(max_calls, dtype=np.int32)
    nargs_a = np.zeros(max_calls, dtype=np.int32)
    ret_idx = np.full(max_calls, -1, dtype=np.int32)
    amode = np.zeros((max_calls, SIM_MAX_ARGS), dtype=np.int32)
    aslot = np.full((max_calls, SIM_MAX_ARGS), -1, dtype=np.int32)
    aconst = np.zeros((max_calls, SIM_MAX_ARGS), dtype=np.uint64)
    ameta = np.zeros((max_calls, SIM_MAX_ARGS), dtype=np.uint64)
    aaux = np.zeros((max_calls, SIM_MAX_ARGS), dtype=np.uint64)

    # Pass 1: the set of ret-backed copyout indices.  Memory-backed
    # indices (COPYOUT instructions) are deliberately absent — the sim
    # has no guest memory to read, so results routed through memory
    # degrade to the arg default, on device and oracle alike.
    ret_backed: set[int] = set()
    for p in _walk_calls(words):
        co = int(words[p + 1])
        if co != EXEC_NO_COPYOUT:
            ret_backed.add(co)

    c = -1
    for p in _walk_calls(words):
        c += 1
        if c >= max_calls:
            raise ValueError(
                f"template has more than {max_calls} calls")
        call_id[c] = int(words[p]) & 0xFFFFFFFF
        co = int(words[p + 1])
        if co != EXEC_NO_COPYOUT and co < SIM_MAX_COPYOUT:
            ret_idx[c] = co
        na = int(words[p + 2])
        if na > SIM_MAX_ARGS:
            raise ValueError(f"call {c} has {na} args (max "
                             f"{SIM_MAX_ARGS}, executor failf's these)")
        nargs_a[c] = na
        q = p + 3
        for i in range(na):
            kind = int(words[q])
            if kind == EXEC_ARG_CONST:
                s = word2slot.get(q + 2)
                if s is None:
                    amode[c, i] = MODE_CONST
                    aconst[c, i] = words[q + 2]
                    ameta[c, i] = words[q + 1]
                elif et is not None and bool(et.is_proc[s]):
                    amode[c, i] = MODE_PROC
                    aslot[c, i] = s
                    aconst[c, i] = et.aux0[s]
                    ameta[c, i] = et.proc_meta_concrete[s]
                    aaux[c, i] = et.proc_meta_default[s]
                else:
                    amode[c, i] = MODE_SLOT
                    aslot[c, i] = s
                    ameta[c, i] = words[q + 1]
            elif kind == EXEC_ARG_RESULT:
                amode[c, i] = MODE_RESULT
                idx = int(words[q + 2])
                if idx in ret_backed and idx < SIM_MAX_COPYOUT:
                    aslot[c, i] = idx
                aconst[c, i] = words[q + 5]  # type default
                ameta[c, i] = words[q + 3]  # op_div
                aaux[c, i] = words[q + 4]  # op_add
            else:
                amode[c, i] = MODE_ZERO  # data/csum read as 0
            q = _skip_arg(words, q)

    return SimTable(ncalls=c + 1, call_id=call_id, nargs=nargs_a,
                    ret_idx=ret_idx, amode=amode, aslot=aslot,
                    aconst=aconst, ameta=ameta, aaux=aaux)


def build_sim_table(et, max_calls: int = SIM_MAX_CALLS) -> SimTable:
    """Lower an ops/emit.ExecTemplate: device-mutable slots become
    MODE_SLOT/MODE_PROC references into the mutant's value vector."""
    vw = np.asarray(et.val_word)
    word2slot = {int(vw[s]): s for s in range(vw.shape[0]) if vw[s] >= 0}
    return _lower(np.asarray(et.words), word2slot, et, max_calls)


def build_sim_table_from_words(words,
                               max_calls: int = SIM_MAX_CALLS
                               ) -> SimTable:
    """Lower a raw assembled exec stream (no template): every arg is
    static, so sim_exec_host needs no value vector."""
    return _lower(np.asarray(words, dtype=np.uint64), {}, None, max_calls)


def _bswap64(v: int) -> int:
    return int.from_bytes((v & MASK64).to_bytes(8, "little"), "big")


def transform_const(v: int, meta: int, pid: int) -> int:
    """The executor's read_arg const-path transform: pid stride, then
    the big-endian swap of the low `size` bytes (executor swap_bytes:
    bswap64 then shift down).  Bitfields are NOT applied at call-arg
    positions."""
    v = (v + (meta >> 32) * pid) & MASK64
    if (meta >> 8) & 1:
        sz = meta & 0xFF
        sz = 1 if sz < 1 else (8 if sz > 8 else sz)
        v = _bswap64(v) >> (64 - 8 * sz)
    return v


def resolve_arg(table: SimTable, c: int, i: int, vals, covals,
                codone, pid: int) -> int:
    """Resolve call c's arg i to the u64 the executor would pass."""
    mode = int(table.amode[c, i])
    if mode == MODE_ZERO:
        return 0
    if mode == MODE_CONST:
        return transform_const(int(table.aconst[c, i]),
                               int(table.ameta[c, i]), pid)
    if mode == MODE_SLOT:
        return transform_const(int(vals[table.aslot[c, i]]) & MASK64,
                               int(table.ameta[c, i]), pid)
    if mode == MODE_PROC:
        pv = int(vals[table.aslot[c, i]]) & MASK64
        if pv == MASK64:
            raw, meta = 0, int(table.aaux[c, i])
        else:
            raw = (int(table.aconst[c, i]) + pv) & MASK64
            meta = int(table.ameta[c, i])
        return transform_const(raw, meta, pid)
    # MODE_RESULT
    idx = int(table.aslot[c, i])
    if idx >= 0 and codone[idx]:
        v = int(covals[idx])
    else:
        v = int(table.aconst[c, i])
    div = int(table.ameta[c, i])
    if div:
        v //= div
    return (v + int(table.aaux[c, i])) & MASK64


def sim_exec_host(table: SimTable, vals=None,
                  alive_bits: int = MASK64, pid: int = 0):
    """Run a lowered table through the host SimKernelModel with the
    executor's sequencing (skip dead calls, _exit on a full crash so
    later calls never run, persist ret-backed copyouts only on
    errno == 0).  Returns (edges u32[C,E], valid bool[C,E],
    ret u64[C], errno i32[C], status i32[C]) — the exact outputs of
    the device kernel, which is what makes this the parity oracle."""
    C = table.call_id.shape[0]
    edges = np.zeros((C, SIM_EDGE_SLOTS), dtype=np.uint32)
    valid = np.zeros((C, SIM_EDGE_SLOTS), dtype=bool)
    ret = np.zeros(C, dtype=np.uint64)
    errno = np.zeros(C, dtype=np.int32)
    status = np.zeros(C, dtype=np.int32)

    model = SimKernelModel(pid)
    covals = [0] * SIM_MAX_COPYOUT
    codone = [False] * SIM_MAX_COPYOUT
    for c in range(int(table.ncalls)):
        if not (alive_bits >> c) & 1:
            continue
        args = [resolve_arg(table, c, i, vals, covals, codone, pid)
                for i in range(int(table.nargs[c]))]
        r = model.exec(int(table.call_id[c]), args)
        edges[c] = np.asarray(r.edges, dtype=np.uint64).astype(np.uint32)
        valid[c] = r.valid
        ret[c] = r.ret
        errno[c] = r.errno
        if r.crashed:
            status[c] = STATUS_CRASHED
            break  # the executor _exits: later calls never run
        status[c] = STATUS_RAN
        ri = int(table.ret_idx[c])
        if ri >= 0 and r.errno == 0:
            covals[ri] = r.ret
            codone[ri] = True
    return edges, valid, ret, errno, status
