"""Raw PC coverage set — for UI/reporting, not fitness
(reference: pkg/cover/cover.go:7-30)."""

from __future__ import annotations

from typing import Iterable


class Cover(set):
    def merge(self, raw: Iterable[int]) -> None:
        # int() coercion keeps numpy scalars out of serialization.
        self.update(int(pc) for pc in raw)

    def merge_diff(self, raw: Iterable[int]) -> list[int]:
        """Merge and return newly-added PCs (each at most once even if
        the raw trace repeats it)."""
        new = []
        for pc in raw:
            pc = int(pc)
            if pc not in self:
                self.add(pc)
                new.append(pc)
        return new

    def serialize(self) -> list[int]:
        return sorted(self)
