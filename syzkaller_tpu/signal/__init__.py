"""Feedback-signal model (reference: pkg/signal, pkg/cover)."""

from syzkaller_tpu.signal.signal import Signal, from_raw, minimize_corpus  # noqa: F401
from syzkaller_tpu.signal.cover import Cover  # noqa: F401
