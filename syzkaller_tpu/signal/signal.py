"""Prioritized coverage signal.

A Signal maps edge hashes to small priorities; novelty ("is any of
this new at >= prio?") is the test run on every executed call
(reference: pkg/signal/signal.go:11-166).  This is the CPU reference
for the TPU bitmap-plane implementation in ops/signal.py, which must
make identical accept/reject decisions.
"""

from __future__ import annotations

from typing import Iterable, Optional


class Signal:
    """dict-backed signal; elements are uint32 edge hashes, priorities
    int8 (reference: pkg/signal/signal.go:16)."""

    __slots__ = ("m",)

    def __init__(self, m: Optional[dict[int, int]] = None):
        self.m: dict[int, int] = m if m is not None else {}

    def __len__(self) -> int:
        return len(self.m)

    def empty(self) -> bool:
        return not self.m

    def __contains__(self, elem: int) -> bool:
        return elem in self.m

    def copy(self) -> "Signal":
        return Signal(dict(self.m))

    def serialize(self) -> tuple[list[int], list[int]]:
        elems = list(self.m.keys())
        prios = [self.m[e] for e in elems]
        return elems, prios

    @staticmethod
    def deserialize(elems: list[int], prios: list[int]) -> "Signal":
        assert len(elems) == len(prios), "corrupted serial signal"
        return Signal(dict(zip(elems, prios)))

    def diff(self, s1: "Signal") -> "Signal":
        """Elements of s1 new to self at their prio
        (reference: pkg/signal/signal.go:73-88)."""
        res: dict[int, int] = {}
        for e, p1 in s1.m.items():
            p = self.m.get(e)
            if p is not None and p >= p1:
                continue
            res[e] = p1
        return Signal(res)

    def diff_raw(self, raw: Iterable[int], prio: int) -> "Signal":
        """(reference: pkg/signal/signal.go:90-102).  Elements are
        coerced to python ints so numpy scalars from executor output
        never leak into serialization."""
        res: dict[int, int] = {}
        for e in raw:
            e = int(e)
            p = self.m.get(e)
            if p is not None and p >= prio:
                continue
            res[e] = prio
        return Signal(res)

    def intersection(self, s1: "Signal") -> "Signal":
        """Elements of self present in s1 at >= prio
        (reference: pkg/signal/signal.go:104-115)."""
        res: dict[int, int] = {}
        for e, p in self.m.items():
            p1 = s1.m.get(e)
            if p1 is not None and p1 >= p:
                res[e] = p
        return Signal(res)

    def merge(self, s1: "Signal") -> None:
        """Max-merge s1 into self (reference: pkg/signal/signal.go:117-131)."""
        for e, p1 in s1.m.items():
            p = self.m.get(e)
            if p is None or p < p1:
                self.m[e] = p1


def from_raw(raw: Iterable[int], prio: int) -> Signal:
    return Signal({int(e): prio for e in raw})


def minimize_corpus(corpus: list[tuple[Signal, object]]) -> list[object]:
    """Greedy set cover of the corpus by signal: keep one (max-prio,
    largest-signal-first) witness per element
    (reference: pkg/signal/signal.go:138-166)."""
    order = sorted(range(len(corpus)), key=lambda i: -len(corpus[i][0]))
    covered: dict[int, tuple[int, int]] = {}  # elem -> (prio, corpus idx)
    for i in order:
        sig, _ = corpus[i]
        for e, p in sig.m.items():
            prev = covered.get(e)
            if prev is None or p > prev[0]:
                covered[e] = (p, i)
    indices = {idx for _, idx in covered.values()}
    return [corpus[i][1] for i in indices]
