"""Kernel build + boot-image pipeline (reference: pkg/kernel/kernel.go
configure/build and syz-ci/manager.go:235 image creation).

Three stages, each a plain `make` invocation against a kernel source
tree so the same driver runs on a stub makefile tree in tests and a
real kernel checkout on capable hosts:

  configure(): `make O=<out> <defconfig>` then append the fuzzing
      config fragment (KCOV, KASAN, debug info, panic-on-warn — the
      reference writes the same set) and re-normalize with
      `make olddefconfig`.
  build():     `make O=<out> -j<n> bzImage` -> the compressed kernel.
  make_image(): package a bootable artifact for vm/qemu.py's
      -kernel/-initrd mode: the bzImage plus a minimal initramfs
      (newc cpio written directly — no root, no loop devices) that
      contains /init and the tz-executor binary, so a booted guest
      can immediately serve the fuzzing fork-server.

The real-kernel path is documented in docs/real_kernel.md; nothing
here requires root or kvm — only `make` and a kernel tree.
"""

from __future__ import annotations

import io
import os
import shutil
import subprocess
from dataclasses import dataclass, field
from typing import Optional

#: Config fragment the fuzzing kernel needs (reference:
#: pkg/kernel/kernel.go + docs/linux/setup.md recommended configs).
FUZZING_CONFIG = """\
CONFIG_KCOV=y
CONFIG_KCOV_INSTRUMENT_ALL=y
CONFIG_KCOV_ENABLE_COMPARISONS=y
CONFIG_DEBUG_FS=y
CONFIG_DEBUG_INFO=y
CONFIG_KASAN=y
CONFIG_KASAN_INLINE=y
CONFIG_CONFIGFS_FS=y
CONFIG_SECURITYFS=y
CONFIG_FAULT_INJECTION=y
CONFIG_FAULT_INJECTION_DEBUG_FS=y
CONFIG_FAILSLAB=y
CONFIG_FAIL_PAGE_ALLOC=y
CONFIG_PANIC_ON_OOPS=y
CONFIG_PANIC_TIMEOUT=86400
"""


class BuildError(Exception):
    pass


@dataclass
class KernelBuilder:
    kernel_src: str
    out_dir: str
    defconfig: str = "defconfig"
    config_fragment: str = ""
    jobs: int = 4
    make: str = "make"
    env: dict = field(default_factory=dict)

    def _run(self, *target: str) -> str:
        env = dict(os.environ)
        env.update(self.env)
        res = subprocess.run(
            [self.make, f"O={self.out_dir}", *target],
            cwd=self.kernel_src, capture_output=True, text=True,
            env=env)
        if res.returncode != 0:
            raise BuildError(
                f"make {' '.join(target)} failed:\n{res.stderr[-2048:]}")
        return res.stdout

    def configure(self) -> str:
        """defconfig + fuzzing fragment + olddefconfig; returns the
        .config path."""
        os.makedirs(self.out_dir, exist_ok=True)
        self._run(self.defconfig)
        cfg = os.path.join(self.out_dir, ".config")
        with open(cfg, "a") as f:
            f.write("\n# tz fuzzing fragment\n")
            f.write(FUZZING_CONFIG)
            if self.config_fragment:
                f.write(self.config_fragment)
                if not self.config_fragment.endswith("\n"):
                    f.write("\n")
        self._run("olddefconfig")
        return cfg

    def build(self) -> str:
        """Build the compressed kernel; returns the bzImage path."""
        self._run(f"-j{self.jobs}", "bzImage")
        for rel in ("arch/x86/boot/bzImage", "bzImage"):
            p = os.path.join(self.out_dir, rel)
            if os.path.exists(p):
                return p
        raise BuildError(f"bzImage not found under {self.out_dir}")

    def make_image(self, image_dir: str,
                   executor: Optional[str] = None) -> dict:
        """Package {kernel, initrd} for qemu -kernel/-initrd boot.

        The initramfs is a newc cpio with /init (mounts proc/sys/dev,
        brings up loopback, idles on the console so the manager's ssh/
        pipe wiring can take over) and optionally /bin/tz-executor."""
        os.makedirs(image_dir, exist_ok=True)
        bz = self.build()
        kernel_out = os.path.join(image_dir, "bzImage")
        shutil.copyfile(bz, kernel_out)
        init = ("#!/bin/sh\n"
                "mount -t proc none /proc 2>/dev/null\n"
                "mount -t sysfs none /sys 2>/dev/null\n"
                "mount -t devtmpfs none /dev 2>/dev/null\n"
                "ip link set lo up 2>/dev/null\n"
                "echo tz-guest-ready\n"
                "exec /bin/sh\n").encode()
        entries = [("init", 0o755, init),
                   ("bin", 0o40755, b""),
                   ("proc", 0o40755, b""),
                   ("sys", 0o40755, b""),
                   ("dev", 0o40755, b"")]
        if executor and os.path.exists(executor):
            with open(executor, "rb") as f:
                entries.append(("bin/tz-executor", 0o755, f.read()))
        initrd_out = os.path.join(image_dir, "initramfs.cpio")
        with open(initrd_out, "wb") as f:
            f.write(cpio_newc(entries))
        return {"kernel": kernel_out, "initrd": initrd_out}


def cpio_newc(entries: list[tuple[str, int, bytes]]) -> bytes:
    """Minimal newc ("070701") cpio archive writer.

    entries: (name, mode, data); mode 0o40000-bit marks a directory.
    Written directly so image creation needs no cpio binary, no root,
    no loop devices (the reference shells out to external tooling for
    its image step; a library writer keeps this testable anywhere)."""
    out = io.BytesIO()
    ino = 721

    def header(name: str, mode: int, size: int) -> bytes:
        nonlocal ino
        ino += 1
        fields = [
            ino,          # inode
            mode if mode & 0o40000 else (0o100000 | mode),
            0, 0,         # uid, gid
            2 if mode & 0o40000 else 1,  # nlink
            0,            # mtime
            size,
            0, 0, 0, 0,   # devmajor/minor, rdevmajor/minor
            len(name) + 1,
            0,            # check
        ]
        return b"070701" + b"".join(b"%08X" % f for f in fields)

    def align(n: int) -> bytes:
        return b"\0" * ((4 - n % 4) % 4)

    for name, mode, data in entries:
        hdr = header(name, mode, len(data))
        out.write(hdr)
        nb = name.encode() + b"\0"
        out.write(nb)
        out.write(align(len(hdr) + len(nb)))
        out.write(data)
        out.write(align(len(data)))
    trailer = "TRAILER!!!"
    hdr = header(trailer, 0, 0)
    out.write(hdr)
    nb = trailer.encode() + b"\0"
    out.write(nb)
    out.write(align(len(hdr) + len(nb)))
    return out.getvalue()
