"""Continuous integration daemon.

Watches source repos, rebuilds artifacts, restarts managers on
updates, validates images before deployment, runs dashboard patch-test
jobs, and reports build results (reference: syz-ci/syzupdater.go
self-update loop, syz-ci/manager.go:123 manager loop + 235 build,
syz-ci/jobs.go:105 job polling).

Build/fetch are pluggable shell commands from the config so the CI
logic (polling, sequencing, restart, reporting) is hermetic to test —
the reference's kernel `make` invocations become a `build_cmd`.
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from syzkaller_tpu.ci.bisect import _git, GitError
from syzkaller_tpu.utils import log


@dataclass
class ManagedInstance:
    """One manager under CI control (reference: syz-ci Manager)."""
    name: str
    repo: str = ""  # kernel/source repo to watch
    branch: str = "main"
    build_cmd: str = ""  # rebuild artifacts; cwd=repo
    manager_cmd: str = ""  # start the manager process
    # kernel-build pipeline: when kernel_src is set, _build drives
    # configure -> bzImage -> boot image through ci/kernel.py instead
    # of build_cmd (reference: pkg/kernel + syz-ci/manager.go:235)
    kernel_src: str = ""
    kernel_defconfig: str = "defconfig"
    kernel_config_fragment: str = ""
    image_dir: str = ""  # where {bzImage, initramfs.cpio} land
    executor_bin: str = ""  # packed into the initramfs when set
    # runtime state
    current_commit: str = ""
    proc: Optional[subprocess.Popen] = None
    last_build_ok: bool = True
    last_error: str = ""
    image: dict = field(default_factory=dict)


@dataclass
class CIConfig:
    workdir: str = ""
    poll_period_s: float = 60.0
    managers: list[dict] = field(default_factory=list)
    dashboard_addr: str = ""
    dashboard_client: str = ""
    dashboard_key: str = ""


class CI:
    def __init__(self, cfg: CIConfig):
        self.cfg = cfg
        os.makedirs(cfg.workdir, exist_ok=True)
        self.managers = [ManagedInstance(**m) for m in cfg.managers]
        self.stop_ev = threading.Event()
        self.dash = None
        if cfg.dashboard_addr:
            from syzkaller_tpu.dashboard.dashapi import DashClient

            self.dash = DashClient(cfg.dashboard_addr,
                                   cfg.dashboard_client,
                                   cfg.dashboard_key)

    # -- update/build/restart cycle (syz-ci/manager.go:123-233) ----------

    def check_manager(self, m: ManagedInstance) -> bool:
        """Poll the repo; rebuild + restart on new commits.  Returns
        True if an update was deployed."""
        try:
            head = self._poll_repo(m)
        except GitError as e:
            log.logf(0, "ci: poll %s failed: %s", m.name, e)
            return False
        if head == m.current_commit and m.proc is not None \
                and m.proc.poll() is None:
            return False
        if head != m.current_commit:
            log.logf(0, "ci: %s: new commit %s", m.name, head[:12])
            if not self._build(m):
                return False
            m.current_commit = head
        self._restart(m)
        return True

    def _poll_repo(self, m: ManagedInstance) -> str:
        if not m.repo:
            return m.current_commit or "none"
        _git(m.repo, "fetch", "--quiet", check=False)  # offline-safe
        for ref in (f"origin/{m.branch}", m.branch, "HEAD"):
            try:
                return _git(m.repo, "rev-parse", ref)
            except GitError:
                continue
        raise GitError(f"cannot resolve {m.branch} in {m.repo}")

    def _build(self, m: ManagedInstance) -> bool:
        """(reference: syz-ci/manager.go:235 build; failures reported
        to the dashboard as build errors)"""
        if m.kernel_src:
            return self._build_kernel(m)
        if not m.build_cmd:
            m.last_build_ok = True
            return True
        res = subprocess.run(m.build_cmd, shell=True, cwd=m.repo or None,
                             capture_output=True, text=True)
        m.last_build_ok = res.returncode == 0
        m.last_error = res.stderr[-2048:] if res.returncode else ""
        if not m.last_build_ok:
            self._report_build_failure(m)
        return m.last_build_ok

    def _report_build_failure(self, m: ManagedInstance) -> None:
        log.logf(0, "ci: %s: build failed: %s", m.name,
                 m.last_error[-256:])
        if self.dash is not None:
            try:
                self.dash.report_crash(
                    manager=m.name,
                    title=f"{m.name} build error",
                    log=m.last_error)
            except Exception as e:
                log.logf(0, "ci: dashboard report failed: %s", e)

    def _build_kernel(self, m: ManagedInstance) -> bool:
        """configure -> build -> image through the kernel pipeline;
        the produced {kernel, initrd} pair is what a qemu-backed
        manager boots (vm/qemu.py -kernel/-initrd)."""
        from syzkaller_tpu.ci.kernel import BuildError, KernelBuilder

        out_dir = os.path.join(self.cfg.workdir, f"{m.name}-kbuild")
        image_dir = m.image_dir or os.path.join(self.cfg.workdir,
                                                f"{m.name}-image")
        kb = KernelBuilder(kernel_src=m.kernel_src, out_dir=out_dir,
                           defconfig=m.kernel_defconfig,
                           config_fragment=m.kernel_config_fragment)
        try:
            kb.configure()
            m.image = kb.make_image(image_dir, executor=m.executor_bin)
            m.last_build_ok = True
            m.last_error = ""
        except (BuildError, OSError) as e:
            # OSError covers environment failures (no make binary,
            # missing kernel_src) — they must surface as build errors
            # too, not escape with last_build_ok still True
            m.last_build_ok = False
            m.last_error = str(e)[-2048:]
            self._report_build_failure(m)
        return m.last_build_ok

    def _restart(self, m: ManagedInstance) -> None:
        if m.proc is not None and m.proc.poll() is None:
            m.proc.terminate()
            try:
                m.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                m.proc.kill()
                m.proc.wait()
        if not m.manager_cmd:
            return
        logf = open(os.path.join(self.cfg.workdir,
                                 f"{m.name}.log"), "ab")
        m.proc = subprocess.Popen(m.manager_cmd, shell=True,
                                  stdout=logf, stderr=subprocess.STDOUT)
        log.logf(0, "ci: %s: started (pid %d)", m.name, m.proc.pid)

    # -- patch-test jobs (syz-ci/jobs.go:105) ----------------------------

    def poll_jobs(self, test_fn=None) -> Optional[dict]:
        """Claim one dashboard job, apply the patch on a throwaway
        branch, run the test, report the outcome."""
        if self.dash is None:
            return None
        try:
            job = self.dash.job_poll([m.name for m in self.managers])
        except Exception as e:
            log.logf(0, "ci: job poll failed: %s", e)
            return None
        if not job or "id" not in job:
            return None
        m = self.managers[0] if self.managers else None
        ok, error = False, ""
        try:
            if m is not None and m.repo and job.get("patch"):
                # Preserve any local worktree state across the job:
                # stash (tracking whether one was created), and after
                # the test drop both modifications and files the patch
                # added, then restore the stash.
                stashed = "No local changes" not in subprocess.run(
                    ["git", "-C", m.repo, "stash",
                     "--include-untracked"],
                    capture_output=True, text=True).stdout
                res = subprocess.run(
                    ["git", "-C", m.repo, "apply", "--check", "-"],
                    input=job["patch"], capture_output=True, text=True)
                if res.returncode != 0:
                    error = f"patch does not apply: {res.stderr[-512:]}"
                else:
                    subprocess.run(["git", "-C", m.repo, "apply", "-"],
                                   input=job["patch"], capture_output=True,
                                   text=True)
                    try:
                        ok = bool(test_fn(job)) if test_fn is not None \
                            else self._build(m)
                        if not ok:
                            error = m.last_error or "test failed"
                    finally:
                        _git(m.repo, "checkout", "--", ".", check=False)
                        _git(m.repo, "clean", "-fd", check=False)
                if stashed:
                    _git(m.repo, "stash", "pop", check=False)
            else:
                ok = bool(test_fn(job)) if test_fn is not None else False
        except Exception as e:
            error = str(e)
        try:
            self.dash.job_done(job["id"], ok, error)
        except Exception as e:
            log.logf(0, "ci: job_done report failed: %s", e)
        return {"id": job["id"], "ok": ok, "error": error}

    # -- main loop --------------------------------------------------------

    def loop(self) -> None:
        while not self.stop_ev.wait(self.cfg.poll_period_s):
            # The daemon must outlive transient repo/dashboard errors.
            for m in self.managers:
                try:
                    self.check_manager(m)
                except Exception as e:
                    log.logf(0, "ci: %s: check failed: %s", m.name, e)
            try:
                self.poll_jobs()
            except Exception as e:
                log.logf(0, "ci: job cycle failed: %s", e)

    def shutdown(self) -> None:
        self.stop_ev.set()
        for m in self.managers:
            if m.proc is not None and m.proc.poll() is None:
                m.proc.terminate()
