"""Kernel commit bisection.

Drives `git bisect` over a kernel tree with an injectable test
predicate (build + boot + run repro), finding the commit that
introduced — or fixed — a crash (reference: pkg/bisect/bisect.go:19-30
Run; pkg/git git ops).
"""

from __future__ import annotations

import subprocess
from dataclasses import dataclass
from enum import Enum
from typing import Callable, Optional

from syzkaller_tpu.utils import log


class TestResult(Enum):
    __test__ = False  # not a pytest class despite the name
    GOOD = "good"  # does not crash
    BAD = "bad"  # crashes
    SKIP = "skip"  # build/boot failure — cannot test


# predicate(commit_hash) -> TestResult
Pred = Callable[[str], TestResult]


@dataclass
class BisectResult:
    commit: str  # culprit (cause- or fix-) commit
    log: str
    tested: int = 0


class GitError(Exception):
    pass


def _git(repo: str, *args: str, check: bool = True) -> str:
    res = subprocess.run(["git", "-C", repo, *args],
                         capture_output=True, text=True)
    if check and res.returncode != 0:
        raise GitError(f"git {' '.join(args)}: {res.stderr[-512:]}")
    return res.stdout.strip()


def bisect(repo: str, good: str, bad: str, pred: Pred,
           max_tests: int = 64) -> Optional[BisectResult]:
    """Standard cause-bisection: `good` doesn't crash, `bad` does;
    returns the first crashing commit (reference: bisect.go Run)."""
    _git(repo, "bisect", "reset", check=False)
    _git(repo, "bisect", "start")
    out_log = []
    tested = 0
    try:
        _git(repo, "bisect", "bad", bad)
        out = _git(repo, "bisect", "good", good)
        while tested < max_tests:
            if "is the first bad commit" in out:
                commit = out.split()[0]
                return BisectResult(commit=commit,
                                    log="\n".join(out_log),
                                    tested=tested)
            head = _git(repo, "rev-parse", "HEAD")
            tested += 1
            verdict = pred(head)
            out_log.append(f"{head[:12]}: {verdict.value}")
            log.logf(1, "bisect: %s -> %s", head[:12], verdict.value)
            out = _git(repo, "bisect", verdict.value)
        return None
    finally:
        _git(repo, "bisect", "reset", check=False)


def bisect_fix(repo: str, bad: str, good: str, pred: Pred,
               max_tests: int = 64) -> Optional[BisectResult]:
    """Fix-bisection: find the commit that made the crash stop.  Runs
    cause-bisection with the predicate inverted
    (reference: bisect.go fix mode)."""

    def inverted(commit: str) -> TestResult:
        v = pred(commit)
        if v == TestResult.SKIP:
            return v
        return TestResult.BAD if v == TestResult.GOOD else TestResult.GOOD

    return bisect(repo, good=bad, bad=good, pred=inverted,
                  max_tests=max_tests)
