"""Boot-test instances: run the fuzzer inside a fresh VM to validate
an image/build, or replay a repro for bisection
(reference: pkg/instance/instance.go — TestImage, testInstance,
used by syz-ci for build validation and pkg/bisect for testing).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional

from syzkaller_tpu.report import get_reporter
from syzkaller_tpu.utils import log
from syzkaller_tpu.vm.vm import create_pool, monitor_execution


@dataclass
class TestError(Exception):
    """Image/build test failure with context."""
    title: str
    output: bytes = b""

    def __str__(self) -> str:
        return self.title


def framework_cmd(module: str, *args: str) -> str:
    """Shell command running a framework module with the package
    importable regardless of the instance's cwd."""
    import sys
    from pathlib import Path

    import syzkaller_tpu

    root = Path(syzkaller_tpu.__file__).resolve().parents[1]
    argstr = " ".join(args)
    return (f"exec env PYTHONPATH={root} {sys.executable} "
            f"-m {module} {argstr}")


def test_image(cfg, duration_s: float = 30.0) -> None:
    """Boot one instance and fuzz briefly; raises TestError on boot
    failure or crash (reference: instance.go TestImage)."""
    pool = create_pool(cfg)
    reporter = get_reporter(cfg.target_os, ignores=cfg.ignores,
                            suppressions=cfg.suppressions)
    inst = pool.create(0)
    try:
        stop = threading.Event()
        cmd = framework_cmd(
            "syzkaller_tpu.fuzzer.main", "-name", "image-test",
            "-os", cfg.target_os, "-arch", cfg.target_arch,
            "-procs", "1", "-duration", str(duration_s))
        stream = inst.run(duration_s + 60, stop, cmd)
        res = monitor_execution(stream, reporter, exit_ok=True,
                                no_output_timeout=60.0,
                                not_executing_timeout=60.0)
        if res.report is not None:
            raise TestError(title=res.report.title, output=res.output)
        log.logf(0, "image test passed")
    finally:
        inst.close()


def test_repro(cfg, prog_text: bytes, duration_s: float = 30.0
               ) -> Optional[str]:
    """Run one program repeatedly in a fresh instance; returns the
    crash title or None (the bisection predicate's workhorse,
    reference: instance.go testRepro)."""
    import os

    pool = create_pool(cfg)
    reporter = get_reporter(cfg.target_os, ignores=cfg.ignores,
                            suppressions=cfg.suppressions)
    inst = pool.create(0)
    try:
        prog_file = os.path.join(cfg.workdir, "repro.prog")
        with open(prog_file, "wb") as f:
            f.write(prog_text)
        vm_path = inst.copy(prog_file)
        stop = threading.Event()
        cmd = framework_cmd(
            "syzkaller_tpu", "execprog", "-os", cfg.target_os,
            "-arch", cfg.target_arch, "-repeat", "0", vm_path)
        stream = inst.run(duration_s, stop, cmd)
        res = monitor_execution(stream, reporter, exit_ok=True,
                                need_executing=False,
                                no_output_timeout=duration_s)
        return res.report.title if res.report is not None else None
    finally:
        inst.close()
