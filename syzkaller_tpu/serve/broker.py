"""ServePlane: the multi-tenant request broker of the serving plane.

The "Serve" RPC receiver (registered next to "Manager" on the same
transport): many fuzzer VMs (tenants) multiplex their mutation demand
onto one chip's fused drain.  The session discipline is the PR 8
control plane verbatim — Connect mints a (session-epoch, lease) pair;
Poll carries (name, epoch, seq, ack_seq); a bounded per-tenant reply
cache replays duplicate seqs so post-send retries never double-
deliver; leases idle past TZ_SERVE_LEASE_S are reaped with their
reply caches tombstoned — because the serving plane inherits the same
failure modes (VM death, lost replies, manager restarts) and must
give the same answer: at-most-once delivery, zero lost work.

What is new here is the demand/supply ledger:

  * every Poll carries a demand estimate — the tenant's candidate
    backlog plus its exec-rate, EWMA-smoothed broker-side — which the
    batch composer (serve/composer.py) turns into per-tenant row
    allocations,
  * produced mutants land in per-tenant bounded queues (the bound
    shapes COMPOSITION — the composer never produces more than a
    tenant's queue can hold — so nothing is ever dropped on the
    floor),
  * delivery custody mirrors the PR 8 candidate ledger in reverse:
    results ride a reply keyed by its seq in `inflight` until the
    tenant's ack_seq confirms receipt; an abandoned reply (ack_seq
    skipped the seq) returns its results to the FRONT of the queue,
    so kill/reconnect churn reorders but never loses or duplicates,
  * admission quotas extend the PR 8 throttle from protect-the-chip
    to shape-the-fleet: the per-poll allotment is the throttle tier's
    row budget scaled by the tenant's QoS credit, so individual
    tenants shrink before the global breaker trips and a plateaued
    tenant decays to the credit floor instead of starving.

Results ship zero-copy: each pending item's payload is a bytes-like
view into its batch arena (ops/pipeline ExecMutant custom), and the
reply's binary annex (rpc.py _FLAG_ANNEX) concatenates those views on
the socket without a per-mutant copy — the JSON carries only
(tenant, rid, offset, length) refs into the annex.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from collections import deque
from typing import Callable, Optional

from syzkaller_tpu import telemetry
from syzkaller_tpu.health.envsafe import env_float, env_int
from syzkaller_tpu.rpc.replycache import ReplyCache
from syzkaller_tpu.rpc.rpc import ReconnectRequired
from syzkaller_tpu.utils import log

#: Admission tiers (docs/health.md): throttle state -> total result
#: rows a single poll may carry, BEFORE the per-tenant credit scale.
#: "open" still trickles so a recovering tenant has probe work.
SERVE_QUOTA = {"closed": 4096, "half_open": 1024, "open": 256}
#: Reaped tenants' reply caches kept around (bounded, same rationale
#: as manager/rpcserver._MAX_TOMBSTONES).
_MAX_TOMBSTONES = 64
#: EWMA weight for the exec-rate demand smoother (the same
#: settling-vs-straggler tradeoff as telemetry/coverage.EWMA_ALPHA).
EWMA_ALPHA = 0.2

_M_REPLAYS = telemetry.counter(
    "tz_serve_replays_total",
    "duplicate (epoch, seq) serve polls answered from the reply cache")
_M_REAPED = telemetry.counter(
    "tz_serve_leases_reaped_total",
    "tenant leases reaped after TZ_SERVE_LEASE_S without a poll")
_M_REQUEUED = telemetry.counter(
    "tz_serve_results_requeued_total",
    "delivered-but-unacked results returned to the tenant queue")
_M_DROPPED = telemetry.counter(
    "tz_serve_results_dropped_total",
    "undelivered results discarded when their tenant's lease was "
    "reaped")
_M_ANNEX_BYTES = telemetry.counter(
    "tz_serve_annex_bytes_total",
    "zero-copy result payload bytes shipped in reply annexes")
_G_TENANTS = telemetry.gauge(
    "tz_serve_tenants", "tenants holding a live serve lease")
_G_DEMAND = telemetry.gauge(
    "tz_serve_demand_rows",
    "aggregate outstanding tenant demand in rows (backlog minus "
    "queued+inflight results)")


class TenantState:
    """One tenant's queues, session, demand, and QoS bookkeeping."""

    __slots__ = ("name", "last_seen", "reply_cache", "pending",
                 "inflight", "demand_rows", "exec_rate_ewma",
                 "novelty_ewma", "last_novel_ts", "stalled", "credit",
                 "rows_spent", "delivered", "q_gauge", "c_gauge",
                 "m_rows", "m_results")

    def __init__(self, name: str, now: float,
                 cache_entries: Optional[int] = None):
        self.name = name
        self.last_seen = now
        #: (reply, annex) tuples; bounded by entries AND bytes — the
        #: annex tails are arena slices a cached reply pins alive
        #: (rpc/replycache.py).
        self.reply_cache = ReplyCache(entries=cache_entries)
        #: Undelivered results: (rid, payload) with payload a
        #: bytes-like (zero-copy arena view on the device path).
        self.pending: deque = deque()
        #: Results riding un-acked replies: [(seq, [(rid, payload)])].
        self.inflight: list[tuple[int, list[tuple]]] = []
        self.demand_rows = 0
        self.exec_rate_ewma = 0.0
        #: Per-tenant novelty EWMA + plateau latch — the credit
        #: inputs (serve/composer.py).
        self.novelty_ewma = 0.0
        self.last_novel_ts = now
        self.stalled = False
        self.credit = 1.0
        self.rows_spent = 0
        self.delivered = 0
        self.q_gauge = telemetry.gauge(
            "tz_serve_queue_depth",
            "undelivered results queued for one tenant",
            labels={"tenant": name})
        self.c_gauge = telemetry.gauge(
            "tz_serve_credit",
            "one tenant's QoS credit share of device rows",
            labels={"tenant": name})
        self.m_rows = telemetry.counter(
            "tz_serve_rows_total",
            "device rows spent on one tenant's demand",
            labels={"tenant": name})
        self.m_results = telemetry.counter(
            "tz_serve_results_total",
            "novel mutants delivered to one tenant",
            labels={"tenant": name})

    def queued(self) -> int:
        return len(self.pending) + sum(
            len(items) for _seq, items in self.inflight)

    def outstanding_demand(self) -> int:
        """Rows the composer should still produce for this tenant:
        the reported backlog minus what is already queued/in flight."""
        return max(0, self.demand_rows - self.queued())


class ServePlane:
    """The "Serve" RPC receiver + the composer's demand/supply API."""

    def __init__(self, lease_s: Optional[float] = None,
                 queue_cap: Optional[int] = None,
                 reply_cache_size: Optional[int] = None,
                 max_tenants: Optional[int] = None,
                 throttle_fn: Optional[Callable[[], str]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._lock = threading.Lock()
        self.epoch = f"{random.getrandbits(64):016x}"
        self.lease_s = env_float("TZ_SERVE_LEASE_S", 60.0) \
            if lease_s is None else lease_s
        self.queue_cap = max(1, env_int("TZ_SERVE_QUEUE_CAP", 8192)
                             if queue_cap is None else queue_cap)
        self.reply_cache_size = env_int("TZ_RPC_REPLY_CACHE", 128) \
            if reply_cache_size is None else reply_cache_size
        self.max_tenants = max(1, env_int("TZ_SERVE_MAX_TENANTS", 16)
                               if max_tenants is None else max_tenants)
        self.throttle_fn = throttle_fn
        self._clock = clock
        self.tenants: dict[str, TenantState] = {}
        self._tombstones: dict[str, ReplyCache] = {}
        self._rid = 0
        self.reaped_total = 0
        self.replays_total = 0
        # Durability (syzkaller_tpu/durable): when attached, delivery-
        # ledger transitions journal under the store barrier and the
        # tenant queues/credits become a checkpoint section.
        self.durable = None

    def _barrier(self):
        """The store's journal barrier, or a no-op: ledger mutation +
        its WAL record must be atomic w.r.t. checkpoint snapshots
        (durable/store.py module doc)."""
        d = self.durable
        return d.barrier if d is not None else contextlib.nullcontext()

    def _journal(self, kind: str, meta: dict, blob: bytes = b"") -> None:
        d = self.durable
        if d is not None:
            d.journal(kind, meta, blob)

    # -- session plumbing (the PR 8 discipline) ---------------------------

    def _session_precheck(self, params: dict) -> Optional[tuple]:
        epoch = params.get("epoch")
        if not epoch:
            return None
        name = params.get("name", "tenant")
        seq = int(params.get("seq") or 0)
        with self._lock:
            self._reap_locked()
            if epoch != self.epoch:
                raise ReconnectRequired(
                    f"serve epoch {epoch} is stale (broker epoch "
                    f"{self.epoch}); re-Connect")
            t = self.tenants.get(name)
            if t is None:
                cache = self._tombstones.get(name)
                if cache is not None and seq in cache:
                    _M_REPLAYS.inc()
                    self.replays_total += 1
                    return cache[seq]
                raise ReconnectRequired(
                    f"serve lease for {name!r} expired; re-Connect")
            t.last_seen = self._clock()
            cached = t.reply_cache.get(seq)
            if cached is not None:
                _M_REPLAYS.inc()
                self.replays_total += 1
                return cached
        return None

    def _session_commit(self, params: dict, reply: tuple) -> tuple:
        seq = int(params.get("seq") or 0)
        if not params.get("epoch") or not seq:
            return reply
        name = params.get("name", "tenant")
        with self._lock:
            t = self.tenants.get(name)
            if t is not None:
                # Entry + byte bounds live inside ReplyCache
                # (TZ_RPC_REPLY_CACHE / TZ_RPC_REPLY_CACHE_MB) — the
                # byte bound matters most HERE, where cached annex
                # tails pin arena slices.
                t.reply_cache.put(seq, reply)
        return reply

    def _reap_locked(self) -> None:
        now = self._clock()
        expired = [t for t in self.tenants.values()
                   if t.last_seen and now - t.last_seen > self.lease_s]
        for t in expired:
            del self.tenants[t.name]
            self.reaped_total += 1
            _M_REAPED.inc()
            self._journal("serve_reap", {"tenant": t.name})
            self._tombstones[t.name] = t.reply_cache
            while len(self._tombstones) > _MAX_TOMBSTONES:
                del self._tombstones[next(iter(self._tombstones))]
            # Results are tenant-specific: there is no survivor to
            # hand them to (handing them over WOULD be the cross-
            # tenant leak the conservation test forbids) — drop and
            # account.
            dropped = t.queued()
            if dropped:
                _M_DROPPED.inc(dropped)
            t.q_gauge.set(0)
            telemetry.record_event(
                "serve.lease_expire",
                f"{t.name} idle {now - t.last_seen:.0f}s; dropped "
                f"{dropped} undelivered results")
            log.logf(0, "reaped serve tenant %s (idle %.0fs)",
                     t.name, now - t.last_seen)
        _G_TENANTS.set(len(self.tenants))

    def _settle_locked(self, t: TenantState, seq: int,
                       ack_seq: int) -> None:
        """Advance delivery custody: replies the tenant confirmed
        (reply seq <= ack_seq) retire their results; replies the
        tenant abandoned (seq < current, never acked) return their
        results to the FRONT of the queue so redelivery keeps the
        original order."""
        keep: list[tuple[int, list[tuple]]] = []
        requeued: list[tuple] = []
        for bseq, items in t.inflight:
            if bseq <= ack_seq:
                t.delivered += len(items)
            elif bseq < seq:
                requeued.extend(items)
            else:
                keep.append((bseq, items))
        t.inflight = keep
        if requeued:
            _M_REQUEUED.inc(len(requeued))
            t.pending.extendleft(reversed(requeued))

    # -- RPC methods ------------------------------------------------------

    def Connect(self, params: dict) -> dict:
        """Mint (epoch, lease) for a tenant.  A re-Connect under an
        existing name (VM restart, post-reap resync) KEEPS the pending
        result queue — those mutants were produced for this tenant's
        demand and are still its property — but returns in-flight
        items to the queue front, since any un-acked reply died with
        the old connection."""
        name = params.get("name", "tenant")
        with self._barrier(), self._lock:
            self._reap_locked()
            old = self.tenants.get(name)
            if old is None and len(self.tenants) >= self.max_tenants:
                raise RuntimeError(
                    f"serve admission: {self.max_tenants} tenants "
                    "already hold leases (TZ_SERVE_MAX_TENANTS)")
            now = self._clock()
            t = TenantState(name=name, now=now,
                            cache_entries=self.reply_cache_size)
            if old is not None:
                self._settle_locked(old, 1 << 62, 0)
                t.pending = old.pending
                t.novelty_ewma = old.novelty_ewma
                t.credit = old.credit
                t.rows_spent = old.rows_spent
                t.delivered = old.delivered
            self._tombstones.pop(name, None)
            self.tenants[name] = t
            _G_TENANTS.set(len(self.tenants))
            self._journal("serve_connect", {"tenant": name})
            return {"epoch": self.epoch, "lease_s": self.lease_s,
                    "queue_cap": self.queue_cap}

    def Poll(self, params: dict):
        """Demand up, results down.  Returns (reply, annex): the
        annex is the zero-copy concatenation of every shipped
        payload; reply["results"] carries (tenant, rid, off, len)
        refs into it."""
        with self._barrier():
            cached = self._session_precheck(params)
            if cached is not None:
                return cached
            reply = self._poll(params)
            return self._session_commit(params, reply)

    def _poll(self, params: dict) -> tuple:
        name = params.get("name", "tenant")
        demand = params.get("demand") or {}
        seq = int(params.get("seq") or 0)
        ack_seq = int(params.get("ack_seq") or 0)
        max_results = int(params.get("max_results") or (1 << 30))
        with self._lock:
            t = self.tenants.get(name)
            if t is None:  # legacy unsessioned caller
                t = TenantState(name=name, now=self._clock(),
                                cache_entries=self.reply_cache_size)
                self.tenants[name] = t
                _G_TENANTS.set(len(self.tenants))
            if seq:
                self._settle_locked(t, seq, ack_seq)
                self._journal("serve_settle",
                              {"tenant": name, "seq": seq,
                               "ack_seq": ack_seq})
            t.demand_rows = max(0, int(demand.get("backlog") or 0))
            rate = float(demand.get("exec_rate") or 0.0)
            t.exec_rate_ewma += EWMA_ALPHA * (rate - t.exec_rate_ewma)
            # Admission quota: the throttle tier's row budget scaled
            # by this tenant's QoS credit — allotments shrink per
            # tenant before the global breaker trips.
            state = self.throttle_fn() if self.throttle_fn else "closed"
            allot = max(1, int(SERVE_QUOTA.get(state, 256) * t.credit))
            n = min(len(t.pending), allot, max_results)
            items = [t.pending.popleft() for _ in range(n)]
            if seq and items:
                t.inflight.append((seq, list(items)))
                self._journal("serve_issue",
                              {"tenant": name, "seq": seq,
                               "n": len(items)})
            t.q_gauge.set(len(t.pending))
            _G_DEMAND.set(sum(x.outstanding_demand()
                              for x in self.tenants.values()))
            credit = t.credit
        refs, annex, off = [], [], 0
        for rid, payload in items:
            ln = len(payload)
            refs.append({"tenant": name, "rid": rid,
                         "off": off, "len": ln})
            annex.append(payload)
            off += ln
        _M_ANNEX_BYTES.inc(off)
        reply = {"results": refs, "credit": round(credit, 4),
                 "quota": {"state": state, "max_results": allot},
                 "queued": len(t.pending)}
        return reply, annex

    # -- composer-facing supply API ---------------------------------------

    def demands(self) -> dict[str, int]:
        """Per-tenant rows the composer should produce: outstanding
        demand capped by queue headroom (the bound shapes composition;
        nothing is dropped after the fact)."""
        with self._lock:
            return {
                name: min(t.outstanding_demand(),
                          max(0, self.queue_cap - len(t.pending)))
                for name, t in self.tenants.items()}

    def offer(self, tenant: str, payloads: list, rows_spent: int,
              novel: int) -> int:
        """The composer hands one tenant its batch share: `payloads`
        are the novel mutants' bytes-like views, `rows_spent` the
        device rows this tenant's allocation consumed, `novel` the
        plane-novel count (feeds the QoS novelty EWMA).  Returns the
        number queued (0 if the tenant vanished mid-compose)."""
        with self._barrier(), self._lock:
            t = self.tenants.get(tenant)
            if t is None:
                return 0
            rids = []
            for payload in payloads:
                self._rid += 1
                rid = f"{tenant}:{self._rid}"
                rids.append(rid)
                t.pending.append((rid, payload))
            t.rows_spent += rows_spent
            t.q_gauge.set(len(t.pending))
            if payloads or rows_spent:
                self._journal(
                    "serve_offer",
                    {"tenant": tenant, "rids": rids,
                     "lens": [len(p) for p in payloads],
                     "rows_spent": int(rows_spent),
                     "novel": int(novel), "rid_after": self._rid},
                    b"".join(bytes(p) for p in payloads))
        t.m_rows.inc(rows_spent)
        if payloads:
            t.m_results.inc(len(payloads))
        if novel:
            resumed = False
            with self._lock:
                t.last_novel_ts = self._clock()
                if t.stalled:
                    t.stalled = False
                    resumed = True
            if resumed:
                telemetry.record_event(
                    "coverage.resume",
                    f"serve tenant {tenant}: {novel} novel mutants "
                    "after a plateau")
        return len(payloads)

    def reap_expired(self) -> None:
        with self._barrier(), self._lock:
            self._reap_locked()

    # -- durability (syzkaller_tpu/durable) --------------------------------

    def durable_provider(self) -> tuple:
        """Checkpoint section: every tenant's delivery queue + QoS
        state.  In-flight custody is collapsed to the queue front at
        EXPORT (same order _settle_locked would restore), because a
        restarted broker re-mints its epoch and every tenant
        re-Connects — there is no session for the in-flight seqs to
        settle against."""
        with self._lock:
            parts: list[bytes] = []
            tenants: dict = {}
            off = 0
            for name, t in self.tenants.items():
                items = []
                entries = [it for _seq, its in t.inflight
                           for it in its] + list(t.pending)
                for rid, payload in entries:
                    b = bytes(payload)
                    items.append([rid, off, len(b)])
                    parts.append(b)
                    off += len(b)
                tenants[name] = {
                    "credit": t.credit,
                    "novelty_ewma": t.novelty_ewma,
                    "stalled": t.stalled,
                    "rows_spent": t.rows_spent,
                    "delivered": t.delivered,
                    "demand_rows": t.demand_rows,
                    "items": items,
                }
            return ({"rid": self._rid, "tenants": tenants},
                    b"".join(parts))

    def durable_restore(self, state: dict) -> None:
        """Install recovered tenant ledgers (recovery.replay's "serve"
        value).  Recovered tenants get `last_seen = 0` — no live
        lease, so they are never reaped for idling before their VM
        re-Connects, and Connect keeps their pending queue."""
        gauges = []
        with self._lock:
            self._rid = max(self._rid, int(state.get("rid") or 0))
            now = self._clock()
            for name, st in (state.get("tenants") or {}).items():
                t = self.tenants.get(name)
                if t is None:
                    t = TenantState(name=name, now=now,
                                    cache_entries=self.reply_cache_size)
                    t.last_seen = 0.0
                    self.tenants[name] = t
                t.pending = deque(
                    (rid, bytes(payload))
                    for rid, payload in st.get("pending") or [])
                t.credit = float(st.get("credit", 1.0))
                t.novelty_ewma = float(st.get("novelty_ewma", 0.0))
                t.stalled = bool(st.get("stalled", False))
                t.rows_spent = int(st.get("rows_spent", 0))
                t.delivered = int(st.get("delivered", 0))
                gauges.append((t, len(t.pending)))
            _G_TENANTS.set(len(self.tenants))
        for t, depth in gauges:
            t.q_gauge.set(depth)
            t.c_gauge.set(round(t.credit, 4))

    def snapshot(self) -> dict:
        """The /api/serve body (manager/html.py) and the bench/
        stats_snapshot serve block.  The "accounting" key joins the
        device-time ledger's tenant dimension (ISSUE 14) so one fetch
        answers both custody and chargeback."""
        acct = telemetry.ACCOUNTING.dimension_snapshot("tenant")
        with self._lock:
            now = self._clock()
            return {
                "accounting": acct,
                "epoch": self.epoch,
                "lease_s": self.lease_s,
                "queue_cap": self.queue_cap,
                "tenants": {
                    name: {
                        "idle_s": round(now - t.last_seen, 1)
                        if t.last_seen else None,
                        "demand_rows": t.demand_rows,
                        "exec_rate_ewma": round(t.exec_rate_ewma, 2),
                        "queued": len(t.pending),
                        "inflight": sum(len(i) for _s, i in t.inflight),
                        "credit": round(t.credit, 4),
                        "novelty_ewma": round(t.novelty_ewma, 4),
                        "stalled": t.stalled,
                        "rows_spent": t.rows_spent,
                        "delivered": t.delivered,
                    } for name, t in self.tenants.items()},
                "reaped": self.reaped_total,
                "replays": self.replays_total,
            }
