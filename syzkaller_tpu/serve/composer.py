"""BatchComposer: continuous batching onto the fused device drain.

The inference-serving move applied to the fuzzing hot loop: instead
of one consumer draining whole 4096-row fused batches, the composer
fills each batch from MULTIPLE tenants' demand, weighted by QoS
credits, and carries a per-row tenant-id column through the drain so
every produced mutant lands in exactly its requester's queue.

Credit formula (docs/perf.md "The serving plane"):

    c_i = floor + (1 - n*floor) * w_i / SUM(w)      (healthy tenants)
    c_i <- max(floor, c_i * decay)                   (plateaued)

where w_i is the tenant's novelty EWMA (the per-tenant analogue of
the PR 7 `tz_coverage_novel_edges_total{lane=...}` rate the ROADMAP
told this scheduler to consume), `floor` = TZ_SERVE_CREDIT_FLOOR and
`decay` = TZ_SERVE_CREDIT_DECAY.  A tenant with no novel mutant for
TZ_SERVE_STALL_WINDOW_S latches `stalled` (the per-tenant plateau
verdict, same detector shape as telemetry/coverage.py) and its credit
decays geometrically to EXACTLY the floor — never to zero: a starved
tenant could never produce the novel mutant that would justify
re-promoting it.  The first novel verdict after a plateau clears the
latch (the broker emits the `coverage.resume` timeline event) and the
next rebalance restores the demand-weighted share.

Row allocation is largest-remainder over credit shares, capped by
per-tenant outstanding demand and queue headroom, with unused rows
redistributed to tenants that still want them — a batch is only
smaller than `batch_rows` when aggregate demand is.

The `serve.compose` fault seam sits at the top of compose_once: a
scripted fault defers the whole batch (demand intact, nothing
produced) — the composer must tolerate its own scheduling failing
mid-stride, exactly like the manager's lease reaper.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import numpy as np

from syzkaller_tpu import telemetry
from syzkaller_tpu.health.envsafe import env_choice, env_float
from syzkaller_tpu.health.faultinject import FaultInjected, fault_point
from syzkaller_tpu.serve.broker import EWMA_ALPHA, ServePlane
from syzkaller_tpu.serve.plane import TenantPlanes

_M_BATCHES = telemetry.counter(
    "tz_serve_batches_total",
    "fused batches composed from multi-tenant demand")
_M_DEFERRED = telemetry.counter(
    "tz_serve_compose_deferred_total",
    "compose passes deferred by a scripted serve.compose fault")


class BatchComposer:
    """Fills fused batches from tenant queues; see module doc.

    `drain_fn(n_rows) -> (rows, payloads)` produces n_rows exec-ready
    mutants: `rows` a uint8[n, row_bytes] array (the novelty-verdict
    input — the packed delta rows on the device path), `payloads` a
    same-length sequence of bytes-like exec payloads (zero-copy arena
    views from ops/pipeline on the device path; scripted buffers in
    tests).  Injectable so the tier-1 suite runs a host drain with no
    jit compiles."""

    def __init__(self, broker: ServePlane, planes: TenantPlanes,
                 drain_fn: Callable, batch_rows: int = 4096,
                 credit_floor: Optional[float] = None,
                 credit_decay: Optional[float] = None,
                 rebalance_s: Optional[float] = None,
                 stall_window_s: Optional[float] = None,
                 interval_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.broker = broker
        self.planes = planes
        self.drain_fn = drain_fn
        self.batch_rows = max(1, batch_rows)
        self.credit_floor = min(0.5, max(0.0, env_float(
            "TZ_SERVE_CREDIT_FLOOR",
            0.05 if credit_floor is None else credit_floor)))
        self.credit_decay = min(0.99, max(0.01, env_float(
            "TZ_SERVE_CREDIT_DECAY",
            0.5 if credit_decay is None else credit_decay)))
        self.rebalance_s = max(0.0, env_float(
            "TZ_SERVE_REBALANCE_S",
            1.0 if rebalance_s is None else rebalance_s))
        self.stall_window_s = max(0.1, env_float(
            "TZ_SERVE_STALL_WINDOW_S",
            30.0 if stall_window_s is None else stall_window_s))
        self.interval_s = max(0.0, env_float(
            "TZ_SERVE_COMPOSE_INTERVAL_S",
            0.02 if interval_s is None else interval_s))
        # Credit pricing (ISSUE 14): "novelty" weights healthy
        # tenants by their raw novelty EWMA (bit-exact PR 11
        # behavior); "yield" weights by the accounting ledger's
        # novel-edges-per-device-second EWMA, so a tenant burning
        # chip time without discovering anything decays even while
        # technically novel.
        self.price = env_choice("TZ_SERVE_PRICE", "novelty",
                                ("novelty", "yield"))
        self._clock = clock
        self._last_rebalance = clock()
        # Lane tenants (attach_lane): tenants whose rows come from
        # their own drain (e.g. the batched hints lane) instead of
        # the default drain_fn, with the lane label their rows book
        # under in the accounting ledger.
        self._lane_drains: dict[str, Callable] = {}
        self._lane_names: dict[str, str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def attach_lane(self, tenant: str, drain_fn: Callable,
                    lane: Optional[str] = None) -> None:
        """Register `tenant` as a lane tenant: its allocated rows are
        produced by its own `drain_fn(n) -> (rows, payloads)` (e.g.
        HintLane.compose_drain) instead of the shared default drain,
        and book to `tz_acct_device_ms_total{lane=...}` under `lane`
        (default: the tenant name).  QoS credits, plateau latches and
        largest-remainder allocation treat it exactly like any other
        tenant — a plateaued random-mutation tenant's rows rebalance
        toward hints through the ordinary credit formula."""
        self._lane_drains[tenant] = drain_fn
        self._lane_names[tenant] = lane or tenant

    # -- QoS credits -------------------------------------------------------

    def rebalance_credits(self, force: bool = False) -> dict[str, float]:
        """Recompute per-tenant credits from the novelty EWMAs and
        plateau latches.  Rate-limited to rebalance_s unless forced.
        Emits a `serve.credits` timeline event when shares move."""
        now = self._clock()
        if not force and now - self._last_rebalance < self.rebalance_s:
            with self.broker._lock:
                return {n: t.credit
                        for n, t in self.broker.tenants.items()}
        self._last_rebalance = now
        floor, decay = self.credit_floor, self.credit_decay
        moved = []
        with self.broker._lock:
            tenants = list(self.broker.tenants.values())
            for t in tenants:
                # Advance the per-tenant novelty EWMA toward its
                # recent delivery rate; the plateau latch follows the
                # same trailing-window rule as the PR 7 detector.
                if not t.stalled and \
                        now - t.last_novel_ts >= self.stall_window_s:
                    t.stalled = True
                    telemetry.record_event(
                        "coverage.stall",
                        f"serve tenant {t.name}: no novel mutant in "
                        f"{self.stall_window_s:.0f}s")
            healthy = [t for t in tenants if not t.stalled]
            n = len(tenants)
            if self.price == "yield":
                # Yield pricing: weight by the ledger's novelty-per-
                # device-second EWMA.  A tenant the ledger has never
                # seen (or that found nothing per chip-second) weighs
                # zero and lands exactly on the floor.
                yields = telemetry.ACCOUNTING.yield_ewmas("tenant")
                _w = lambda t: max(yields.get(t.name, 0.0), 0.0)
            else:
                _w = lambda t: max(t.novelty_ewma, 0.0)
            wsum = sum(_w(t) for t in healthy)
            for t in tenants:
                old = t.credit
                if t.stalled:
                    # Geometric decay to EXACTLY the floor.
                    t.credit = max(floor, t.credit * decay)
                    if t.credit - floor < 1e-9:
                        t.credit = floor
                elif wsum > 0:
                    w = _w(t)
                    t.credit = floor + (1.0 - n * floor) * (w / wsum)
                else:  # cold start / all-equal: even shares
                    t.credit = 1.0 / max(1, n) if n else 1.0
                t.c_gauge.set(round(t.credit, 4))
                if abs(t.credit - old) > 1e-6:
                    moved.append(f"{t.name}:{old:.2f}->{t.credit:.2f}")
            credits = {t.name: t.credit for t in tenants}
            stalled = {t.name: t.stalled for t in tenants}
            ewma = {t.name: t.novelty_ewma for t in tenants}
        if moved:
            telemetry.record_event(
                "serve.credits", " ".join(sorted(moved)))
        if credits:
            # Idempotent overwrite record, journaled after the broker
            # lock is released (durable/store.py lock-order rule).
            self.broker._journal("credit", {"credits": credits,
                                            "ewma": ewma,
                                            "stalled": stalled})
        return credits

    # -- batch composition -------------------------------------------------

    def allocate(self, credits: dict[str, float],
                 demands: dict[str, int]) -> list[tuple[str, int]]:
        """Largest-remainder fill of one batch: credit shares capped
        by demand, leftovers redistributed to tenants that still want
        rows.  Returns [(tenant, n_rows)] in deterministic (sorted)
        tenant order; SUM(n) <= batch_rows with equality whenever
        aggregate demand allows."""
        want = {t: d for t, d in sorted(demands.items()) if d > 0}
        if not want:
            return []
        total = sum(credits.get(t, 0.0) for t in want) or 1.0
        quota = {t: self.batch_rows * credits.get(t, 0.0) / total
                 for t in want}
        alloc = {t: min(int(quota[t]), want[t]) for t in want}
        # Hand out remaining rows by descending fractional remainder
        # (ties broken by tenant name for determinism), respecting
        # each tenant's demand cap.
        remaining = self.batch_rows - sum(alloc.values())
        order = sorted(want, key=lambda t: (-(quota[t] - int(quota[t])),
                                            t))
        while remaining > 0:
            progressed = False
            for t in order:
                if remaining <= 0:
                    break
                if alloc[t] < want[t]:
                    alloc[t] += 1
                    remaining -= 1
                    progressed = True
            if not progressed:
                break  # aggregate demand < batch_rows
        return [(t, n) for t, n in sorted(alloc.items()) if n > 0]

    def compose_once(self) -> dict:
        """One compose->drain->distribute pass.  Returns a report:
        {"rows": total, "tenants": {name: {"rows", "novel",
        "novel_idx"}}} — empty when there is no demand or the
        serve.compose seam deferred the pass."""
        try:
            fault_point("serve.compose")
        except FaultInjected:
            _M_DEFERRED.inc()
            return {"rows": 0, "tenants": {}, "deferred": True}
        with telemetry.span("serve.compose"):
            credits = self.rebalance_credits()
            demands = self.broker.demands()
            alloc = self.allocate(credits, demands)
            total = sum(n for _t, n in alloc)
            if total == 0:
                return {"rows": 0, "tenants": {}}
            # The per-row tenant-id column the drain carries
            # (ops/pipeline.AssembledBatch.tenants on the device
            # path): row j belongs to tenant_col[j].
            tenant_col = np.concatenate([
                np.full(n, i, np.int32)
                for i, (_t, n) in enumerate(alloc)])
        default_total = sum(
            n for t, n in alloc if t not in self._lane_drains)
        lane_rows_acct: dict[str, int] = {}
        with telemetry.span("serve.dispatch"):
            t_drain = time.perf_counter()
            if not self._lane_drains:
                rows, payloads = self.drain_fn(total)
            else:
                # Segment the batch: default tenants share one
                # drain_fn call; each lane tenant produces its own
                # rows.  Segments stitch back in alloc order so the
                # tenant_col offsets stay aligned.
                d_rows = d_payloads = None
                if default_total:
                    d_rows, d_payloads = self.drain_fn(default_total)
                    d_rows = np.atleast_2d(
                        np.asarray(d_rows, dtype=np.uint8))
                part_rows: list = []
                payloads = []
                off_d = 0
                for t, n in alloc:
                    fn = self._lane_drains.get(t)
                    if fn is None:
                        part_rows.append(d_rows[off_d:off_d + n])
                        payloads.extend(d_payloads[off_d:off_d + n])
                        off_d += n
                    else:
                        r, p = fn(n)
                        part_rows.append(np.atleast_2d(
                            np.asarray(r, dtype=np.uint8)))
                        payloads.extend(p)
                        lane = self._lane_names[t]
                        lane_rows_acct[lane] = \
                            lane_rows_acct.get(lane, 0) + n
                w = max(p.shape[1] for p in part_rows)
                rows = np.zeros((total, w), dtype=np.uint8)
                off = 0
                for p in part_rows:
                    rows[off:off + p.shape[0], :p.shape[1]] = p
                    off += p.shape[0]
            drain_s = time.perf_counter() - t_drain
        # Accounting ledger (ISSUE 14): the drain's host-observed
        # residency is the batch's device time, row-weighted over the
        # allocation — including rows allotted to a tenant reaped
        # mid-compose (it consumed them; conservation holds).  Lane
        # tenants additionally book their share under their lane
        # label (tz_acct_device_ms_total{lane="hints"}); the default
        # drain's rows book to "exploration" so the lane split
        # conserves the batch.
        if lane_rows_acct and default_total:
            lane_rows_acct["exploration"] = default_total
        telemetry.ACCOUNTING.note_batch(
            drain_s, tenant_rows={t: n for t, n in alloc},
            lane_rows=lane_rows_acct or None)
        rows = np.atleast_2d(np.asarray(rows, dtype=np.uint8))
        report: dict = {"rows": total, "tenants": {},
                        "tenant_col": tenant_col,
                        "order": [t for t, _n in alloc]}
        off = 0
        ewmas: dict[str, float] = {}
        for tenant, n in alloc:
            t_rows = rows[off:off + n]
            t_payloads = payloads[off:off + n]
            off += n
            novel = self.planes.verdict(tenant, t_rows)
            idx = np.flatnonzero(novel)
            # Per-tenant plane novelty joins the ledger's yield EWMA
            # (tz_acct_novel_edges_per_device_sec{tenant=...}).
            telemetry.ACCOUNTING.note_novel(
                "tenant", tenant, int(idx.size))
            self.broker.offer(
                tenant, [t_payloads[int(j)] for j in idx],
                rows_spent=n, novel=int(idx.size))
            with self.broker._lock:
                t = self.broker.tenants.get(tenant)
                if t is not None:
                    t.novelty_ewma += EWMA_ALPHA * (
                        idx.size / max(1, n) - t.novelty_ewma)
                    ewmas[tenant] = t.novelty_ewma
            report["tenants"][tenant] = {
                "rows": n, "novel": int(idx.size),
                "novel_idx": [int(j) for j in idx]}
        if ewmas:
            self.broker._journal("credit", {"ewma": ewmas})
        _M_BATCHES.inc()
        return report

    # -- the serving loop --------------------------------------------------

    def start(self) -> None:
        """Continuous serving: compose whenever there is demand, idle
        at interval_s otherwise.  Daemon thread; stop() joins it."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="tz-serve-compose")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                report = self.compose_once()
            except Exception as e:  # the loop survives drain failures
                telemetry.record_event(
                    "serve.compose_error", f"{type(e).__name__}: {e}")
                report = {"rows": 0}
            if report.get("rows", 0) == 0:
                self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
