"""Per-tenant mutant-novelty planes for the serving plane.

One tenant's plane occupancy must not poison another tenant's novelty
verdicts: the fused drain's shared mutant plane (ops/signal) dedups
*production*, but a mutant that is old news to tenant A may be brand
new to tenant B.  Each tenant therefore gets its OWN host-side plane,
sized by TZ_SERVE_PLANE_BITS (2^bits bytes of uint8 — the per-tenant
memory knob; docs/perf.md "The serving plane" has the cost model),
with its own epoch counter so an invalidation (tenant reconnect after
a wedge, an operator reset) is scoped to that tenant alone.

Bucket assignment reuses the EXACT fold rules of the device path
(ops/signal.hash_rows FNV-1a + fold_mutant_idx), reimplemented in
numpy so verdicts here are bit-identical to what a fresh single-
tenant device plane would say — the property the multi-tenant
conservation test pins (ISSUE 12 acceptance: per-tenant verdicts
bit-exact vs running each tenant alone on a fresh plane).

Per-tenant occupancy and fold-false-negative-rate accounting rides
the same discipline as the PR 7 coverage analytics (triage/engine
threads these into its run_analytics() rollup when attached):
labeled gauges `tz_serve_plane_occupancy{tenant=...}` /
`tz_serve_plane_fn_rate{tenant=...}` plus an analytics() dict for
/api/serve.  Everything is host-side numpy under one lock — no jits.
"""

from __future__ import annotations

import threading

import numpy as np

from syzkaller_tpu import telemetry

#: Default per-tenant plane size: 2^20 buckets = 1 MB per tenant —
#: a ~B/2^20 false-drop rate per 4096-row batch, the same
#: memory/recall bargain as the shared mutant plane's 2^22 default
#: scaled down because a tenant sees only its credit share of rows.
PLANE_BITS_DEFAULT = 20

_FNV_OFFSET = np.uint32(0x811C9DC5)
_FNV_PRIME = np.uint32(0x01000193)


def resolve_serve_plane_bits() -> int:
    """TZ_SERVE_PLANE_BITS (envsafe) clamped to the same sane range
    as the shared mutant plane: 10 bits (1 KB, tests) .. 28 bits."""
    from syzkaller_tpu.health.envsafe import env_int

    bits = env_int("TZ_SERVE_PLANE_BITS", PLANE_BITS_DEFAULT)
    return min(max(int(bits), 10), 28)


def hash_rows_np(rows: np.ndarray) -> np.ndarray:
    """FNV-1a over each row's bytes, vectorized across the batch:
    uint8[B, row_bytes] -> uint32[B].  Bit-identical to the device
    fori_loop in ops/signal.hash_rows (numpy uint32 arithmetic wraps
    mod 2^32 exactly as the jitted path does)."""
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    h = np.full(rows.shape[0], _FNV_OFFSET, np.uint32)
    with np.errstate(over="ignore"):
        for j in range(rows.shape[1]):
            h = (h ^ rows[:, j].astype(np.uint32)) * _FNV_PRIME
    return h


def fold_idx_np(h: np.ndarray, bits: int) -> np.ndarray:
    """ops/signal.fold_mutant_idx on the host: identical fold, so a
    tenant plane and a device plane at the same bits agree bucket-
    for-bucket."""
    return ((h ^ (h >> np.uint32(bits)))
            & np.uint32((1 << bits) - 1)).astype(np.int64)


class TenantPlanes:
    """Per-tenant novelty planes + epoch/occupancy accounting."""

    def __init__(self, bits: int | None = None):
        self.bits = resolve_serve_plane_bits() if bits is None \
            else min(max(int(bits), 10), 28)
        self.size = 1 << self.bits
        self._lock = threading.Lock()
        self._planes: dict[str, np.ndarray] = {}
        self._epochs: dict[str, int] = {}
        self._occupancy: dict[str, int] = {}
        self._g_occ: dict[str, object] = {}
        self._g_fn: dict[str, object] = {}
        # Durability (syzkaller_tpu/durable): a DurableStore.journal
        # callable; verdicts journal their folded bucket indices so
        # replay reproduces each tenant's plane without re-hashing.
        self.journal = None
        # Residency ledger (ISSUE 17): one handle for the whole
        # tenant-plane set — host memory, sized by admission cap x
        # 2^bits, the serving plane's only long-lived footprint.
        self._hbm = telemetry.HBM.register(
            "serve", "tenant_planes", device="host", bound_to=self)

    def _ensure_locked(self, tenant: str) -> np.ndarray:
        plane = self._planes.get(tenant)
        if plane is None:
            plane = np.zeros(self.size, np.uint8)
            self._planes[tenant] = plane
            self._epochs[tenant] = 0
            self._occupancy[tenant] = 0
            self._g_occ[tenant] = telemetry.gauge(
                "tz_serve_plane_occupancy",
                "occupied buckets in one tenant's novelty plane",
                labels={"tenant": tenant})
            self._g_fn[tenant] = telemetry.gauge(
                "tz_serve_plane_fn_rate",
                "estimated false-drop rate of one tenant's plane "
                "(occupancy / plane size)",
                labels={"tenant": tenant})
            self._hbm.update(list(self._planes.values()),
                             device="host")
        return plane

    def verdict(self, tenant: str, rows: np.ndarray) -> np.ndarray:
        """Cross-batch novelty verdicts for one tenant's rows:
        bool[B], marking the buckets.  Same within-batch semantics as
        ops/signal.mutant_novelty (duplicates in one batch all read
        the pre-update plane, so all pass) — required for the
        bit-exactness property."""
        rows = np.atleast_2d(np.asarray(rows, dtype=np.uint8))
        idx = fold_idx_np(hash_rows_np(rows), self.bits)
        with self._lock:
            plane = self._ensure_locked(tenant)
            novel = plane[idx] == 0
            plane[idx] = 1
            occ = self._occupancy[tenant] + int(
                np.unique(idx[novel]).size)
            self._occupancy[tenant] = occ
            g_occ, g_fn = self._g_occ[tenant], self._g_fn[tenant]
        g_occ.set(occ)
        g_fn.set(round(occ / self.size, 6))
        if self.journal is not None:
            # After the mutation, outside the lock: replay is an
            # idempotent set-to-1, so racing a checkpoint is harmless
            # (durable/store.py module doc has the lock-order rule).
            self.journal("tplane", {"tenant": tenant,
                                    "bits": int(self.bits)},
                         idx.astype(np.uint32).tobytes())
        return novel

    def invalidate(self, tenant: str) -> int:
        """Zero one tenant's plane and bump its epoch — scoped: no
        other tenant's verdicts change.  Returns the new epoch."""
        with self._lock:
            if tenant not in self._planes:
                self._ensure_locked(tenant)
            self._planes[tenant].fill(0)
            self._occupancy[tenant] = 0
            self._epochs[tenant] += 1
            epoch = self._epochs[tenant]
            g_occ, g_fn = self._g_occ[tenant], self._g_fn[tenant]
        g_occ.set(0)
        g_fn.set(0.0)
        return epoch

    def drop(self, tenant: str) -> None:
        """Forget a reaped tenant's plane (its gauges stay registered
        at their last value; the label set is bounded by the broker's
        admission cap)."""
        with self._lock:
            self._planes.pop(tenant, None)
            self._occupancy.pop(tenant, None)
            self._hbm.update(list(self._planes.values()),
                             device="host")

    def epoch(self, tenant: str) -> int:
        with self._lock:
            return self._epochs.get(tenant, 0)

    def durable_provider(self) -> tuple:
        """Checkpoint section: every tenant's plane, zlib-packed with
        per-tenant slices in the meta (DurableStore.register)."""
        from syzkaller_tpu.durable.checkpoint import pack_section

        with self._lock:
            parts: list[bytes] = []
            tenants: dict = {}
            off = 0
            for name, plane in self._planes.items():
                b = pack_section(plane)
                tenants[name] = {"off": off, "len": len(b),
                                 "epoch": self._epochs.get(name, 0)}
                parts.append(b)
                off += len(b)
        return ({"bits": int(self.bits), "tenants": tenants},
                b"".join(parts))

    def durable_restore(self, state: dict) -> None:
        """Install recovered tenant planes (recovery.replay's
        "tenant_planes" value).  A bits mismatch (operator changed
        TZ_SERVE_PLANE_BITS across the restart) discards the recovered
        planes — novelty verdicts then cold-start, which only costs
        re-serving old news, never correctness."""
        bits = int(state.get("bits") or self.bits)
        if bits != self.bits:
            return
        gauges = []
        with self._lock:
            for name, arr in (state.get("planes") or {}).items():
                arr = np.asarray(arr, dtype=np.uint8)
                if arr.size != self.size:
                    continue
                plane = self._ensure_locked(name)
                plane[:] = arr
                occ = int(np.count_nonzero(plane))
                self._occupancy[name] = occ
                self._epochs[name] = int(
                    (state.get("epochs") or {}).get(name, 0))
                gauges.append((self._g_occ[name], self._g_fn[name],
                               occ))
        for g_occ, g_fn, occ in gauges:
            g_occ.set(occ)
            g_fn.set(round(occ / self.size, 6))

    def analytics(self) -> dict:
        """Per-tenant occupancy/FN-rate rollup — threaded through the
        triage engine's run_analytics() when attached, and the
        /api/serve payload."""
        with self._lock:
            return {
                tenant: {
                    "occupancy": occ,
                    "fn_rate": round(occ / self.size, 6),
                    "epoch": self._epochs.get(tenant, 0),
                }
                for tenant, occ in self._occupancy.items()}
