"""ServeTenant: the fuzzer-VM side of the serving plane.

Wraps an RPCClient in the PR 8 session discipline against the "Serve"
receiver: connect() mints the session (and re-arms it transparently on
ReconnectRequired), poll() reports demand and collects results from
the reply's zero-copy annex.  The annex arrives as one bytes object;
each result is sliced out by its (off, len) ref — a memoryview slice,
so the per-mutant copy the annex path exists to avoid never happens
client-side either.

Delivery hygiene lives here too: every ref's tenant tag is checked
against this client's name (a mismatch is the cross-tenant leak the
conservation test forbids — fail loudly, not quietly), and a bounded
rid window dedups redeliveries that session replays make possible at
the application layer even though the transport is at-most-once.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

from syzkaller_tpu.rpc.rpc import RPCClient

#: Remembered delivered rids (dedup window).  Redelivery can only
#: reorder within a few polls, so a small window is plenty.
_RID_WINDOW = 4096


class CrossTenantLeak(RuntimeError):
    """A delivered result's tenant tag did not match this client."""


class ServeTenant:
    """One fuzzer VM's handle on the serving plane."""

    def __init__(self, addr: tuple[str, int], name: str, **kw):
        self.name = name
        self.client = RPCClient(addr, name=name, **kw)
        self.lease_s: Optional[float] = None
        self.queue_cap: Optional[int] = None
        self.credit: float = 0.0
        self.quota: dict = {}
        self._seen: OrderedDict[str, None] = OrderedDict()

    def connect(self) -> dict:
        """Serve.Connect + arm the idempotent session; installed as
        the client's on_reconnect so a reaped lease or broker restart
        resyncs mid-poll without the caller noticing."""
        reply = self.client.call("Serve.Connect", {"name": self.name})
        self.lease_s = reply.get("lease_s")
        self.queue_cap = reply.get("queue_cap")
        self.client.set_session(reply["epoch"],
                                on_reconnect=self.connect)
        return reply

    def poll(self, backlog: int, exec_rate: float = 0.0,
             max_results: Optional[int] = None) -> list[tuple[str, bytes]]:
        """One demand/supply exchange: reports (backlog, exec_rate),
        returns this poll's fresh results as [(rid, payload)] sliced
        zero-copy out of the reply annex."""
        params = {"demand": {"backlog": int(backlog),
                             "exec_rate": float(exec_rate)}}
        if max_results is not None:
            params["max_results"] = int(max_results)
        reply, annex = self.client.call_session(
            "Serve.Poll", params, want_annex=True)
        # Annex-safety audit (ISSUE 16 S1): by the time this returns,
        # the transport has already drained the ENTIRE annex off the
        # socket — rpc._recv_frame reads header, payload, and annex
        # before any decompress/decode can raise — so a malformed ref
        # below (or a raise in this loop) can never leave the pooled
        # connection mid-frame.  App-level decode errors here are
        # therefore safe to propagate without closing the socket.
        self.credit = reply.get("credit", self.credit)
        self.quota = reply.get("quota", self.quota)
        view = memoryview(annex) if annex else memoryview(b"")
        out: list[tuple[str, bytes]] = []
        for ref in reply.get("results", []):
            if ref.get("tenant") != self.name:
                raise CrossTenantLeak(
                    f"result {ref.get('rid')!r} for tenant "
                    f"{ref.get('tenant')!r} delivered to {self.name!r}")
            rid = ref["rid"]
            if rid in self._seen:
                continue
            self._seen[rid] = None
            while len(self._seen) > _RID_WINDOW:
                self._seen.popitem(last=False)
            out.append((rid, view[ref["off"]:ref["off"] + ref["len"]]))
        return out

    def close(self) -> None:
        self.client.close()
