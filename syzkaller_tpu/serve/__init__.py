"""Multi-tenant serving plane (ISSUE 12).

Continuous batching of many fuzzer VMs onto the one fused
mutate→emit-compact→novel_any drain: demand flows up through the
sessioned "Serve" RPC (broker.ServePlane), QoS credits turn per-tenant
novelty EWMAs into row shares (composer.BatchComposer), per-tenant
novelty planes keep one tenant's occupancy from poisoning another's
verdicts (plane.TenantPlanes), and results ship back zero-copy as
reply-annex views (client.ServeTenant).  docs/perf.md "The serving
plane" has the anatomy and the tenants-per-chip math.
"""

from syzkaller_tpu.serve.broker import SERVE_QUOTA, ServePlane, TenantState
from syzkaller_tpu.serve.client import ServeTenant
from syzkaller_tpu.serve.composer import BatchComposer
from syzkaller_tpu.serve.plane import TenantPlanes

__all__ = [
    "SERVE_QUOTA",
    "BatchComposer",
    "ServePlane",
    "ServeTenant",
    "TenantPlanes",
    "TenantState",
]
