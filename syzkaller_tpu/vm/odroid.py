"""Odroid board backend: physical ARM boards with hard power-cycle
recovery.

Like `isolated` (ssh to a physical machine) but with out-of-band
recovery: when the board stops answering, it is power-cycled through a
controllable USB hub port before waiting for reboot (reference:
vm/odroid/odroid.go — ssh plumbing + USB-hub port power control).
"""

from __future__ import annotations

import subprocess
import time

from syzkaller_tpu.vm.isolated import IsolatedInstance
from syzkaller_tpu.vm.vmimpl import (BootError, Env, Instance, PoolImpl,
                                     register_vm_type)
from syzkaller_tpu.utils import log


class OdroidInstance(IsolatedInstance):
    def __init__(self, workdir: str, index: int, env: Env, target: str):
        cfg = env.config
        # command template that toggles the hub port, e.g.
        # "uhubctl -l {hub} -p {port} -a {action}"
        self.power_cmd = cfg.get("power_cmd", "")
        self.hub = cfg.get("hub", "")
        self.power_port = str(cfg.get("power_port", "1"))
        try:
            super().__init__(workdir, index, env, target)
        except BootError:
            # dead on arrival: hard power-cycle once, then retry
            self.power_cycle()
            super().__init__(workdir, index, env, target)

    def power_cycle(self) -> None:
        """(reference: odroid.go power-cycle via USB hub)"""
        if not self.power_cmd:
            return
        for action in ("off", "on"):
            cmd = self.power_cmd.format(hub=self.hub,
                                        port=self.power_port,
                                        action=action)
            subprocess.run(cmd, shell=True, capture_output=True)
            if action == "off":
                time.sleep(3)
        log.logf(0, "odroid: power-cycled %s", self.host)
        time.sleep(10)  # board boot starts

    def close(self) -> None:
        super().close()
        # leave the board powered; the next create() deals with hangs


class OdroidPool(PoolImpl):
    def __init__(self, env: Env):
        self.env = env
        self.targets = list(env.config.get("targets", []))
        if not self.targets:
            raise BootError("odroid: config must list targets")

    def count(self) -> int:
        return len(self.targets)

    def create(self, workdir: str, index: int) -> Instance:
        return OdroidInstance(workdir, index, self.env,
                              self.targets[index])


register_vm_type("odroid", OdroidPool)
