"""adb VM backend: Android devices over adb.

Console comes from a USB-serial adapter when configured, else from
adb logcat/dmesg; recovery is reboot-based (reference: vm/adb/adb.go —
device list, adb ssh-less copy/run, console tty detection, battery
check hooks).
"""

from __future__ import annotations

import subprocess
import threading
from typing import Optional

from syzkaller_tpu.vm.vmimpl import (BootError, Env, Instance, OutputStream,
                                     PoolImpl, pump_fd, register_vm_type)


class AdbInstance(Instance):
    def __init__(self, workdir: str, index: int, env: Env, device: str):
        self.workdir = workdir
        self.env = env
        self.device = device
        self.console_tty = env.config.get("console", "")
        self._procs: list[subprocess.Popen] = []
        self._adb("wait-for-device", timeout_s=10 * 60)
        self._adb("shell", "echo ok", timeout_s=60)
        # the fuzzer needs a writable exec dir (reference: adb.go /data)
        self.target_dir = env.config.get("target_dir", "/data/local/tmp")
        self._adb("shell", f"mkdir -p {self.target_dir}", timeout_s=60)

    def _adb(self, *args: str, timeout_s: float = 60.0) -> bytes:
        cmd = ["adb", "-s", self.device, *args]
        try:
            res = subprocess.run(cmd, capture_output=True,
                                 timeout=timeout_s)
        except (subprocess.TimeoutExpired, OSError) as e:
            raise BootError(f"adb {args[0]} failed: {e}") from e
        if res.returncode != 0:
            raise BootError(f"adb {args[0]} failed: "
                            f"{res.stderr.decode()[-512:]}")
        return res.stdout

    def copy(self, host_src: str) -> str:
        import os

        dst = f"{self.target_dir}/{os.path.basename(host_src)}"
        self._adb("push", host_src, dst, timeout_s=300)
        self._adb("shell", f"chmod 755 {dst}")
        return dst

    def forward(self, port: int) -> str:
        # adb reverse: device-side connections to this port reach the
        # host (reference: adb.go Forward).
        self._adb("reverse", f"tcp:{port}", f"tcp:{port}")
        return f"127.0.0.1:{port}"

    def run(self, timeout_s: float, stop: threading.Event,
            command: str) -> OutputStream:
        stream = OutputStream()
        # console: serial tty if configured, else dmesg -w on-device
        if self.console_tty:
            con = subprocess.Popen(
                ["cat", self.console_tty], stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL)
        else:
            con = subprocess.Popen(
                ["adb", "-s", self.device, "shell", "dmesg -w"],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
        self._procs.append(con)
        proc = subprocess.Popen(
            ["adb", "-s", self.device, "shell", command],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        self._procs.append(proc)

        # Console keeps draining for a grace window after the shell
        # channel dies: a device panic kills the shell first while the
        # oops is still flushing over dmesg/serial.
        shell_pump = pump_fd(proc.stdout, stream, proc, stop, timeout_s,
                             finish_stream=False)

        def pump_console():
            import time as _time

            grace_deadline = None
            while not stop.is_set() and con.poll() is None:
                if proc.poll() is not None and grace_deadline is None:
                    grace_deadline = _time.monotonic() + 10.0
                if grace_deadline is not None \
                        and _time.monotonic() > grace_deadline:
                    break
                chunk = con.stdout.read1(1 << 14)
                if not chunk:
                    break
                stream.put(chunk)
            shell_pump.join()
            stream.finish(stream.error)

        threading.Thread(target=pump_console, daemon=True).start()
        return stream

    def diagnose(self) -> bytes:
        try:
            return self._adb("shell", "dmesg", timeout_s=30)
        except BootError:
            return b""

    def close(self) -> None:
        for p in self._procs:
            if p.poll() is None:
                p.kill()
        # reboot to a clean state (reference: adb.go reboot recovery)
        if self.env.config.get("reboot_on_close", False):
            try:
                self._adb("reboot", timeout_s=30)
            except BootError:
                pass


class AdbPool(PoolImpl):
    def __init__(self, env: Env):
        self.env = env
        self.devices = list(env.config.get("devices", []))
        if not self.devices:
            raise BootError("adb: config must list devices")

    def count(self) -> int:
        return len(self.devices)

    def create(self, workdir: str, index: int) -> Instance:
        return AdbInstance(workdir, index, self.env, self.devices[index])


register_vm_type("adb", AdbPool)
