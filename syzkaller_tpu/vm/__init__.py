from syzkaller_tpu.vm.vm import Pool, Instance, create_pool, monitor_execution
from syzkaller_tpu.vm.vmimpl import BootError, Env

__all__ = ["Pool", "Instance", "create_pool", "monitor_execution",
           "BootError", "Env"]
