"""Local VM backend: "instances" are host subprocesses.

The workhorse for hermetic end-to-end tests and for fuzzing the
simulated kernel: each instance is a private workdir, copy is a file
copy, forward is the identity (same host), and run spawns the command
as a subprocess whose merged stdout/stderr is the "console".  This
plays the role the qemu backend plays in production but with zero
boot cost — the analogue of the reference's pattern of exercising
manager logic without kernels (SURVEY.md §4).
"""

from __future__ import annotations

import os
import shutil
import subprocess
import threading
from typing import Optional

from syzkaller_tpu.vm.vmimpl import (Env, Instance, OutputStream, PoolImpl,
                                     pump_fd, register_vm_type)


class LocalInstance(Instance):
    def __init__(self, workdir: str, index: int, env: Env):
        self.workdir = workdir
        self.index = index
        self.env = env
        self._proc: Optional[subprocess.Popen] = None

    def copy(self, host_src: str) -> str:
        dst = os.path.join(self.workdir, os.path.basename(host_src))
        if os.path.abspath(host_src) != os.path.abspath(dst):
            shutil.copy2(host_src, dst)
            shutil.copymode(host_src, dst)
        return dst

    def forward(self, port: int) -> str:
        return f"127.0.0.1:{port}"

    def run(self, timeout_s: float, stop: threading.Event,
            command: str) -> OutputStream:
        stream = OutputStream()
        proc = subprocess.Popen(
            command, shell=True, cwd=self.workdir,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            preexec_fn=os.setsid if hasattr(os, "setsid") else None)
        self._proc = proc

        def on_exit():
            code = proc.returncode
            if code not in (0, None) and not stop.is_set():
                return RuntimeError(f"command exited with status {code}")
            return None

        pump_fd(proc.stdout, stream, proc, stop, timeout_s, on_exit)
        return stream

    def close(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            try:
                # Kill the whole process group (the command may have
                # spawned executors).
                os.killpg(os.getpgid(self._proc.pid), 9)
            except (OSError, ProcessLookupError):
                self._proc.kill()
            self._proc.wait()


class LocalPool(PoolImpl):
    def __init__(self, env: Env):
        self.env = env
        self._count = int(env.config.get("count", 1))

    def count(self) -> int:
        return self._count

    def create(self, workdir: str, index: int) -> Instance:
        os.makedirs(workdir, exist_ok=True)
        return LocalInstance(workdir, index, self.env)


register_vm_type("local", LocalPool)
