"""Isolated VM backend: remote physical machines over ssh.

For fuzzing hardware that can't be virtualized.  Recovery is
reboot-based: when the connection is lost the instance waits for the
machine to come back (reference: vm/isolated/isolated.go — targets
list, reboot wait loop, console via ssh dmesg -w).
"""

from __future__ import annotations

import subprocess
import threading
import time
from typing import Optional

from syzkaller_tpu.vm.vmimpl import (BootError, Env, Instance, OutputStream,
                                     PoolImpl, pump_fd, register_vm_type,
                                     run_ssh, ssh_args)


class IsolatedInstance(Instance):
    def __init__(self, workdir: str, index: int, env: Env, target: str):
        self.workdir = workdir
        self.index = index
        self.env = env
        host, _, port = target.partition(":")
        self.host = host
        self.port = int(port or 22)
        self.target_dir = env.config.get("target_dir", "/tmp/tz-fuzz")
        self._wait_alive(timeout_s=10 * 60)
        self._ssh(f"mkdir -p {self.target_dir}")
        self._console_proc: Optional[subprocess.Popen] = None

    def _ssh_base(self) -> list[str]:
        return ["ssh", *ssh_args(self.env.sshkey, self.env.ssh_user,
                                 self.port),
                f"{self.env.ssh_user}@{self.host}"]

    def _ssh(self, command: str, timeout_s: float = 60.0) -> bytes:
        return run_ssh(self._ssh_base() + [command], timeout_s=timeout_s)

    def _wait_alive(self, timeout_s: float) -> None:
        """Wait for the machine to answer ssh — also the post-crash
        reboot wait (reference: isolated.go waitForReboot)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                self._ssh("true", timeout_s=15)
                return
            except (BootError, subprocess.TimeoutExpired):
                time.sleep(10)
        raise BootError(f"isolated machine {self.host} unreachable")

    def copy(self, host_src: str) -> str:
        import os

        dst = f"{self.target_dir}/{os.path.basename(host_src)}"
        run_ssh(["scp", *ssh_args(self.env.sshkey, self.env.ssh_user,
                                  self.port, scp=True),
                 host_src,
                 f"{self.env.ssh_user}@{self.host}:{dst}"], timeout_s=300)
        return dst

    def forward(self, port: int) -> str:
        # Remote forward created per run() (ssh -R); guests dial this.
        self._fwd_port = port
        return f"127.0.0.1:{port}"

    def run(self, timeout_s: float, stop: threading.Event,
            command: str) -> OutputStream:
        stream = OutputStream()
        args = ["ssh", *ssh_args(self.env.sshkey, self.env.ssh_user,
                                 self.port)]
        fwd = getattr(self, "_fwd_port", None)
        if fwd:
            args += ["-R", f"{fwd}:127.0.0.1:{fwd}"]
        args += [f"{self.env.ssh_user}@{self.host}",
                 # dmesg -w interleaves the kernel console with the
                 # command's own output (reference: isolated.go console)
                 f"dmesg -wT & {command}"]
        proc = subprocess.Popen(args, stdin=subprocess.DEVNULL,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)
        self._console_proc = proc
        pump_fd(proc.stdout, stream, proc, stop, timeout_s)
        return stream

    def close(self) -> None:
        if self._console_proc is not None and \
                self._console_proc.poll() is None:
            self._console_proc.kill()
            self._console_proc.wait()


class IsolatedPool(PoolImpl):
    def __init__(self, env: Env):
        self.env = env
        self.targets = list(env.config.get("targets", []))
        if not self.targets:
            raise BootError("isolated: config must list targets")

    def count(self) -> int:
        return len(self.targets)

    def create(self, workdir: str, index: int) -> Instance:
        return IsolatedInstance(workdir, index, self.env,
                                self.targets[index])


register_vm_type("isolated", IsolatedPool)
