"""VM facade + execution monitor.

Wraps backend pools/instances with workdir management and implements
monitor_execution: the console-scanning loop that turns raw output
into crash reports and detects silent deaths (reference: vm/vm.go:30-110
Pool/Instance wrappers, vm.go:110+ MonitorExecution with its
no-output [3 min] and not-executing [3 min] timeouts).
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from dataclasses import dataclass
from typing import Optional

from syzkaller_tpu.report import Report, Reporter
from syzkaller_tpu.vm.vmimpl import (BootError, Env, Instance, OutputStream,
                                     create_pool_impl)

NO_OUTPUT_TIMEOUT = 3 * 60.0  # reference: vm/vm.go noOutputTimeout
NOT_EXECUTING_TIMEOUT = 3 * 60.0
MAX_CRASH_TAIL_WAIT = 10.0  # drain window after an oops appears
EXECUTING_MARKER = b"executing program"

_ = Instance  # re-exported


class Pool:
    """(reference: vm/vm.go:30-64)"""

    def __init__(self, impl, workdir: str):
        self._impl = impl
        self.workdir = workdir

    def count(self) -> int:
        return self._impl.count()

    def create(self, index: int) -> Instance:
        if not 0 <= index < self.count():
            raise ValueError(f"invalid VM index {index}/{self.count()}")
        wd = os.path.join(self.workdir, f"instance-{index}")
        shutil.rmtree(wd, ignore_errors=True)
        os.makedirs(wd, exist_ok=True)
        return self._impl.create(wd, index)


def create_pool(cfg) -> Pool:
    """Build a Pool from a manager Config (reference: vm/vm.go:52)."""
    workdir = os.path.join(cfg.workdir, "instances")
    os.makedirs(workdir, exist_ok=True)
    env = Env(name=cfg.name, os=cfg.target_os, arch=cfg.target_arch,
              workdir=workdir, image=cfg.image, sshkey=cfg.sshkey,
              ssh_user=cfg.ssh_user, config=dict(cfg.vm))
    if "count" not in env.config:
        env.config["count"] = cfg.count
    return Pool(create_pool_impl(cfg.type, env), workdir)


@dataclass
class MonitorResult:
    report: Optional[Report]  # crash found (None = clean finish)
    output: bytes
    timed_out: bool = False
    lost_connection: bool = False


def monitor_execution(stream: OutputStream, reporter: Reporter,
                      need_executing: bool = True,
                      no_output_timeout: float = NO_OUTPUT_TIMEOUT,
                      not_executing_timeout: float = NOT_EXECUTING_TIMEOUT,
                      exit_ok: bool = False) -> MonitorResult:
    """Consume an instance's output stream until it crashes, goes
    silent, stops executing programs, or finishes
    (reference: vm/vm.go:110-207 MonitorExecution)."""
    output = bytearray()
    last_output = time.monotonic()
    last_executing = time.monotonic()
    scanned_pos = 0

    def synthetic(title: str, **kw) -> MonitorResult:
        rep = Report(title=title, output=bytes(output),
                     report=bytes(output[-(16 << 10):]))
        return MonitorResult(report=rep, output=bytes(output), **kw)

    while True:
        now = time.monotonic()
        chunk = stream.get(timeout=5.0)
        if chunk is None:
            if stream.finished:
                # Stream over: crashed executor/lost machine vs clean end.
                rep = reporter.parse(bytes(output))
                if rep is not None:
                    return MonitorResult(report=rep, output=bytes(output))
                if isinstance(stream.error, TimeoutError):
                    # Run-duration rotation is a clean finish, not a
                    # crash (reference: vm.go timeout handling).
                    return MonitorResult(report=None, output=bytes(output),
                                         timed_out=True)
                if stream.error is not None:
                    return synthetic("lost connection to test machine",
                                     lost_connection=True)
                if exit_ok:
                    return MonitorResult(report=None, output=bytes(output))
                return synthetic("lost connection to test machine",
                                 lost_connection=True)
            if now - last_output > no_output_timeout:
                return synthetic("no output from test machine",
                                 timed_out=True)
            if need_executing and now - last_executing > not_executing_timeout:
                return synthetic("test machine is not executing programs",
                                 timed_out=True)
            continue
        output += chunk
        last_output = now
        if EXECUTING_MARKER in chunk or \
                EXECUTING_MARKER in output[max(0, len(output)
                                               - len(chunk) - 64):]:
            last_executing = now
        # Scan only fresh data (minus an overlap for split lines).
        scan_from = max(0, scanned_pos - 512)
        if reporter.contains_crash(bytes(output[scan_from:])):
            _drain_tail(stream, output)
            rep = reporter.parse(bytes(output))
            if rep is None:  # raced with an ignore rule; keep watching
                scanned_pos = len(output)
                continue
            return MonitorResult(report=rep, output=bytes(output))
        scanned_pos = len(output)


def _drain_tail(stream: OutputStream, output: bytearray,
                wait_s: float = MAX_CRASH_TAIL_WAIT) -> None:
    """After an oops, keep collecting for a bounded window so the
    report includes the full stack trace (vm.go waitForOutput)."""
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        chunk = stream.get(timeout=0.2)
        if chunk is None:
            if stream.finished:
                return
            continue
        output += chunk


__all__ = ["Pool", "Instance", "create_pool", "monitor_execution",
           "MonitorResult", "BootError"]
