"""GCE VM backend: Google Compute Engine instances.

Instances are created from an image via the gcloud CLI, reached over
ssh, with the serial console streamed through `gcloud compute
connect-to-serial-port` (reference: vm/gce/gce.go — instance create/
delete via the GCE API, serial console reader, ssh/scp plumbing via
pkg/gce).
"""

from __future__ import annotations

import subprocess
import threading
import time
from typing import Optional

from syzkaller_tpu.vm.vmimpl import (BootError, Env, Instance, OutputStream,
                                     PoolImpl, pump_fd, register_vm_type,
                                     run_ssh, ssh_args)


def _gcloud(args: list[str], timeout_s: float = 300.0) -> bytes:
    try:
        res = subprocess.run(["gcloud", "compute", *args],
                             capture_output=True, timeout=timeout_s)
    except (subprocess.TimeoutExpired, OSError) as e:
        raise BootError(f"gcloud {args[0]}: {e}") from e
    if res.returncode != 0:
        raise BootError(f"gcloud {args[0]}: {res.stderr.decode()[-512:]}")
    return res.stdout


class GCEInstance(Instance):
    def __init__(self, workdir: str, index: int, env: Env):
        self.workdir = workdir
        self.env = env
        cfg = env.config
        self.zone = cfg.get("zone", "us-central1-b")
        self.machine_type = cfg.get("machine_type", "e2-standard-2")
        self.image = cfg.get("gce_image", "")
        self.name = f"{env.name or 'tz'}-{index}"
        self.preemptible = bool(cfg.get("preemptible", True))
        args = ["instances", "create", self.name,
                "--zone", self.zone,
                "--machine-type", self.machine_type]
        if self.image:
            args += ["--image", self.image]
        if self.preemptible:
            args.append("--preemptible")
        _gcloud(args, timeout_s=600)
        self.ip = _gcloud(
            ["instances", "describe", self.name, "--zone", self.zone,
             "--format=value(networkInterfaces[0].accessConfigs[0].natIP)"],
        ).decode().strip()
        self._wait_ssh(10 * 60)
        self._console: Optional[subprocess.Popen] = None

    def _wait_ssh(self, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                run_ssh(["ssh", *ssh_args(self.env.sshkey,
                                          self.env.ssh_user, 22),
                         f"{self.env.ssh_user}@{self.ip}", "true"],
                        timeout_s=15)
                return
            except BootError:
                time.sleep(10)
        raise BootError(f"GCE instance {self.name}: ssh never came up")

    def copy(self, host_src: str) -> str:
        import os

        dst = "/" + os.path.basename(host_src)
        run_ssh(["scp", *ssh_args(self.env.sshkey, self.env.ssh_user,
                                  22, scp=True), host_src,
                 f"{self.env.ssh_user}@{self.ip}:{dst}"], timeout_s=600)
        return dst

    def forward(self, port: int) -> str:
        self._fwd_port = port
        return f"127.0.0.1:{port}"

    def run(self, timeout_s: float, stop: threading.Event,
            command: str) -> OutputStream:
        stream = OutputStream()
        # serial console carries the oopses (reference: gce.go console)
        self._console = subprocess.Popen(
            ["gcloud", "compute", "connect-to-serial-port", self.name,
             "--zone", self.zone],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            stdin=subprocess.DEVNULL)
        args = ["ssh", *ssh_args(self.env.sshkey, self.env.ssh_user, 22)]
        fwd = getattr(self, "_fwd_port", None)
        if fwd:
            args += ["-R", f"{fwd}:127.0.0.1:{fwd}"]
        args += [f"{self.env.ssh_user}@{self.ip}", command]
        proc = subprocess.Popen(args, stdin=subprocess.DEVNULL,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)
        con = self._console

        # Merge ssh + serial console; the console keeps draining for a
        # grace window after the ssh channel dies — a guest panic kills
        # sshd first while the oops is still flushing over serial (same
        # merger shape as the qemu backend).
        ssh_pump = pump_fd(proc.stdout, stream, proc, stop, timeout_s,
                           finish_stream=False)

        def pump_console():
            grace_deadline = None
            while not stop.is_set() and con.poll() is None:
                if proc.poll() is not None and grace_deadline is None:
                    grace_deadline = time.monotonic() + 10.0
                if grace_deadline is not None \
                        and time.monotonic() > grace_deadline:
                    break
                chunk = con.stdout.read1(1 << 14)
                if not chunk:
                    break
                stream.put(chunk)
            ssh_pump.join()
            stream.finish(stream.error)

        threading.Thread(target=pump_console, daemon=True).start()
        return stream

    def close(self) -> None:
        if self._console is not None and self._console.poll() is None:
            self._console.kill()
        try:
            _gcloud(["instances", "delete", self.name, "--zone",
                     self.zone, "--quiet"], timeout_s=600)
        except BootError:
            pass


class GCEPool(PoolImpl):
    def __init__(self, env: Env):
        self.env = env
        self._count = int(env.config.get("count", 1))

    def count(self) -> int:
        return self._count

    def create(self, workdir: str, index: int) -> Instance:
        return GCEInstance(workdir, index, self.env)


register_vm_type("gce", GCEPool)
