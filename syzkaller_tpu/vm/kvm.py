"""kvm VM backend: lightweight lkvm (kvmtool) sandboxes.

Boots a kernel directly with lkvm sandbox mode — no disk image, the
host filesystem is shared read-only; much faster churn than qemu for
crash-heavy fuzzing (reference: vm/kvm/kvm.go — lkvm setup/sandbox
scripts, console via lkvm stdout).
"""

from __future__ import annotations

import os
import shutil
import subprocess
import threading

from syzkaller_tpu.vm.vmimpl import (BootError, Env, Instance, OutputStream,
                                     PoolImpl, pump_fd, register_vm_type)


class KvmInstance(Instance):
    def __init__(self, workdir: str, index: int, env: Env):
        self.workdir = workdir
        self.index = index
        self.env = env
        cfg = env.config
        self.lkvm = cfg.get("lkvm", "lkvm")
        self.kernel = cfg.get("kernel", "")
        self.cmdline = cfg.get("cmdline", "")
        self.cpus = int(cfg.get("cpu", 1))
        self.mem_mb = int(cfg.get("mem", 1024))
        self.sandbox_name = f"tz-kvm-{index}"
        if not self.kernel:
            raise BootError("kvm: config must set kernel")
        self._proc = None
        self.shared_dir = os.path.join(workdir, "shared")
        os.makedirs(self.shared_dir, exist_ok=True)

    def copy(self, host_src: str) -> str:
        dst = os.path.join(self.shared_dir, os.path.basename(host_src))
        shutil.copy2(host_src, dst)
        # visible inside the sandbox under /host (lkvm 9p share)
        return f"/host/{os.path.basename(host_src)}"

    def forward(self, port: int) -> str:
        return f"127.0.0.1:{port}"  # lkvm user-net reaches the host

    def run(self, timeout_s: float, stop: threading.Event,
            command: str) -> OutputStream:
        stream = OutputStream()
        script = os.path.join(self.workdir, "run.sh")
        with open(script, "w") as f:
            f.write("#!/bin/sh\n" + command + "\n")
        os.chmod(script, 0o755)
        args = [self.lkvm, "sandbox", "--disk", self.sandbox_name,
                "--kernel", self.kernel,
                "--params", f"slub_debug=UZ {self.cmdline}".strip(),
                "--mem", str(self.mem_mb), "--cpus", str(self.cpus),
                "--network", "mode=user",
                "--sandbox", script,
                "--9p", f"{self.shared_dir},host"]
        try:
            proc = subprocess.Popen(args, stdin=subprocess.DEVNULL,
                                    stdout=subprocess.PIPE,
                                    stderr=subprocess.STDOUT,
                                    cwd=self.workdir)
        except OSError as e:
            raise BootError(f"failed to start lkvm: {e}") from e
        self._proc = proc
        pump_fd(proc.stdout, stream, proc, stop, timeout_s)
        return stream

    def close(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()
            self._proc.wait()
        subprocess.run([self.lkvm, "rm", "-n", self.sandbox_name],
                       capture_output=True)


class KvmPool(PoolImpl):
    def __init__(self, env: Env):
        self.env = env
        self._count = int(env.config.get("count", 1))

    def count(self) -> int:
        return self._count

    def create(self, workdir: str, index: int) -> Instance:
        return KvmInstance(workdir, index, self.env)


register_vm_type("kvm", KvmPool)
