"""VM backend plugin interface.

Backends register a Pool constructor by type name; a Pool boots
Instances which expose copy/forward/run/close (reference:
vm/vmimpl/vmimpl.go:21-78 — Pool/Instance interfaces, ctor registry,
BootError).  Console/command output streams through an OutputStream:
a queue of byte chunks plus a terminal error slot, the Python shape of
the reference's (outc <-chan []byte, errc <-chan error) pair.
"""

from __future__ import annotations

import queue
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional


class BootError(Exception):
    """Infrastructure (not kernel-bug) boot failure; the caller retries
    with a fresh instance (reference: vmimpl.go:58-66)."""


@dataclass
class Env:
    """Backend-independent creation params
    (reference: vmimpl.go:30-44)."""
    name: str = ""
    os: str = "test"
    arch: str = "64"
    workdir: str = ""
    image: str = ""
    sshkey: str = ""
    ssh_user: str = "root"
    debug: bool = False
    timeouts_scale: float = 1.0
    config: dict = field(default_factory=dict)  # vm-type blob


class OutputStream:
    """Console/command output: chunks via get(), terminal status via
    .error / .finished."""

    _EOF = object()

    def __init__(self):
        self._q: queue.Queue = queue.Queue()
        self.error: Optional[Exception] = None
        self.finished = False

    def put(self, chunk: bytes) -> None:
        self._q.put(chunk)

    def finish(self, error: Optional[Exception] = None) -> None:
        self.error = error
        self._q.put(self._EOF)

    def get(self, timeout: Optional[float] = None) -> Optional[bytes]:
        """Next chunk, or None on EOF/timeout (check .finished)."""
        if self.finished:
            return None
        try:
            chunk = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if chunk is self._EOF:
            self.finished = True
            return None
        return chunk


class Instance:
    """One VM (reference: vmimpl.go:46-56)."""

    def copy(self, host_src: str) -> str:
        """Copy a host file into the instance; returns the VM path."""
        raise NotImplementedError

    def forward(self, port: int) -> str:
        """Set up VM→host forwarding for the host port; returns the
        address to use inside the VM."""
        raise NotImplementedError

    def run(self, timeout_s: float, stop: threading.Event,
            command: str) -> OutputStream:
        """Run command in the VM; the stream carries merged console +
        command output (reference: vmimpl.go:52-55)."""
        raise NotImplementedError

    def diagnose(self) -> bytes:
        """Extra debugging info on hang (e.g. sysrq dumps)."""
        return b""

    def close(self) -> None:
        raise NotImplementedError


class PoolImpl:
    """(reference: vmimpl.go:21-28)"""

    def count(self) -> int:
        raise NotImplementedError

    def create(self, workdir: str, index: int) -> Instance:
        raise NotImplementedError


_CTORS: dict[str, Callable[[Env], PoolImpl]] = {}


def register_vm_type(name: str, ctor: Callable[[Env], PoolImpl]) -> None:
    _CTORS[name] = ctor


def create_pool_impl(typ: str, env: Env) -> PoolImpl:
    from syzkaller_tpu.vm import (adb, gce, isolated, kvm,  # noqa: F401
                                  local, odroid, qemu)

    ctor = _CTORS.get(typ)
    if ctor is None:
        raise ValueError(f"unknown VM type {typ!r} "
                         f"(known: {sorted(_CTORS)})")
    return ctor(env)


# -- shared helpers (reference: vmimpl.go ssh/scp/merger utils) ----------


def pump_fd(fd_file, stream: OutputStream, proc: subprocess.Popen,
            stop: threading.Event, timeout_s: float,
            on_exit: Optional[Callable[[], Optional[Exception]]] = None,
            finish_stream: bool = True
            ) -> threading.Thread:
    """Pump a file object into an OutputStream until EOF/stop/timeout;
    kills proc on stop/timeout (the vmimpl merger+timeout pattern).

    Requested stops and run-duration timeouts are clean finishes
    (error=None / TimeoutError) — only unexpected process death is an
    error.  With finish_stream=False the caller owns stream.finish()
    (used when a console merger must drain after process death).
    """

    def loop():
        deadline = time.monotonic() + timeout_s
        timed_out = False
        try:
            while True:
                if stop.is_set() or time.monotonic() > deadline:
                    timed_out = not stop.is_set()
                    proc.kill()
                    break
                chunk = fd_file.read1(1 << 14) \
                    if hasattr(fd_file, "read1") else fd_file.read(1 << 14)
                if not chunk:
                    break
                stream.put(chunk)
        except (OSError, ValueError):
            pass
        proc.wait()
        if stop.is_set():
            err: Optional[Exception] = None
        elif timed_out or time.monotonic() > deadline:
            err = TimeoutError("run duration elapsed")
        else:
            err = on_exit() if on_exit is not None else None
        if finish_stream:
            stream.finish(err)
        else:
            stream.error = err

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    return t


def run_ssh(args: list[str], timeout_s: float = 60.0) -> bytes:
    """One-shot helper for scp/ssh control commands."""
    try:
        res = subprocess.run(args, capture_output=True, timeout=timeout_s)
    except subprocess.TimeoutExpired as e:
        raise BootError(f"{' '.join(args[:2])} timed out") from e
    if res.returncode != 0:
        raise BootError(
            f"{' '.join(args[:2])} failed: {res.stderr.decode()[-512:]}")
    return res.stdout


def ssh_args(sshkey: str, user: str, port: int = 0,
             scp: bool = False) -> list[str]:
    """Common ssh/scp options (reference: vmimpl.go SSHArgs).  The
    port flag differs between the tools (ssh -p vs scp -P), so it is
    emitted per-tool here — passing ssh's -p to scp would be parsed
    as scp's preserve-times flag."""
    args = ["-o", "StrictHostKeyChecking=no",
            "-o", "UserKnownHostsFile=/dev/null",
            "-o", "BatchMode=yes",
            "-o", "IdentitiesOnly=yes",
            "-o", "ConnectTimeout=10"]
    if port:
        args += ["-P" if scp else "-p", str(port)]
    if sshkey:
        args += ["-i", sshkey]
    return args
