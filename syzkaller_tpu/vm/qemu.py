"""qemu VM backend.

Boots qemu-system-* with per-arch machine args, waits for ssh, copies
binaries via scp, runs commands over ssh with the serial console
merged into the output stream (reference: vm/qemu/qemu.go:34-99 arch
table, 101-226 ctor/Boot, 228-420 ssh wait + Copy, 422+ Run).
"""

from __future__ import annotations

import os
import socket
import subprocess
import threading
import time
from typing import Optional

from syzkaller_tpu.vm.vmimpl import (BootError, Env, Instance, OutputStream,
                                     PoolImpl, pump_fd, register_vm_type,
                                     run_ssh, ssh_args)

# Per-arch qemu binaries and machine args
# (reference: vm/qemu/qemu.go:34-99 archConfigs).
ARCH_CONFIGS: dict[str, dict] = {
    "amd64": {
        "qemu": "qemu-system-x86_64",
        "args": ["-enable-kvm", "-cpu", "host,migratable=off"],
        "net": "e1000",
    },
    "386": {
        "qemu": "qemu-system-i386",
        "args": [],
        "net": "e1000",
    },
    "arm64": {
        "qemu": "qemu-system-aarch64",
        "args": ["-machine", "virt,virtualization=on", "-cpu", "cortex-a57"],
        "net": "virtio-net-pci",
    },
    "arm": {
        "qemu": "qemu-system-arm",
        "args": ["-machine", "vexpress-a15"],
        "net": "virtio-net-device",
    },
    "ppc64le": {
        "qemu": "qemu-system-ppc64",
        "args": ["-machine", "pseries"],
        "net": "virtio-net-pci",
    },
    "riscv64": {
        "qemu": "qemu-system-riscv64",
        "args": ["-machine", "virt"],
        "net": "virtio-net-pci",
    },
}


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class QemuInstance(Instance):
    def __init__(self, workdir: str, index: int, env: Env):
        self.workdir = workdir
        self.index = index
        self.env = env
        cfg = env.config
        self.arch_cfg = ARCH_CONFIGS.get(env.arch)
        if self.arch_cfg is None:
            raise BootError(f"qemu: unsupported arch {env.arch!r}")
        self.mem_mb = int(cfg.get("mem", 2048))
        self.cpus = int(cfg.get("cpu", 2))
        self.kernel = cfg.get("kernel", "")
        self.initrd = cfg.get("initrd", "")
        self.cmdline = cfg.get("cmdline", "")
        self.qemu_args = cfg.get("qemu_args", "")
        self.ssh_port = _free_port()
        self._fwd_ports: list[tuple[int, int]] = []
        self._proc: Optional[subprocess.Popen] = None
        self._console = OutputStream()
        self._boot(timeout_s=float(cfg.get("boot_timeout", 10 * 60)))

    # -- boot -------------------------------------------------------------

    def _boot(self, timeout_s: float) -> None:
        a = self.arch_cfg
        netdev = (f"user,id=net0,restrict=on,"
                  f"hostfwd=tcp:127.0.0.1:{self.ssh_port}-:22")
        args = [a["qemu"], "-m", str(self.mem_mb), "-smp", str(self.cpus),
                "-display", "none", "-serial", "stdio", "-no-reboot",
                "-device", f"{a['net']},netdev=net0", "-netdev", netdev,
                *a["args"]]
        if self.env.image == "9p":
            args += ["-fsdev",
                     f"local,id=fsdev0,path=/,security_model=none",
                     "-device",
                     "virtio-9p-pci,fsdev=fsdev0,mount_tag=/dev/root"]
        elif self.env.image:
            args += ["-drive", f"file={self.env.image},index=0,media=disk"]
        if self.kernel:
            cmdline = ("root=/dev/sda console=ttyS0 earlyprintk=serial "
                       "oops=panic panic_on_warn=1 panic=86400 "
                       + self.cmdline)
            args += ["-kernel", self.kernel, "-append", cmdline]
            if self.initrd:
                args += ["-initrd", self.initrd]
        if self.qemu_args:
            args += self.qemu_args.split()
        try:
            self._proc = subprocess.Popen(
                args, stdin=subprocess.DEVNULL, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, cwd=self.workdir)
        except OSError as e:
            raise BootError(f"failed to start {a['qemu']}: {e}") from e
        self._console_stop = threading.Event()
        self._console_buf = bytearray()
        self._console_thread = threading.Thread(target=self._pump_console,
                                                daemon=True)
        self._console_thread.start()
        self._wait_ssh(timeout_s)

    def _pump_console(self) -> None:
        try:
            while not self._console_stop.is_set():
                chunk = self._proc.stdout.read1(1 << 14)
                if not chunk:
                    break
                self._console_buf += chunk
                self._console.put(chunk)
        except (OSError, ValueError):
            pass
        self._console.finish()

    def _wait_ssh(self, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._proc.poll() is not None:
                # Let the console pump drain the death message before
                # reporting (it exits once the pipe hits EOF).
                self._console_thread.join(timeout=2.0)
                raise BootError(
                    "qemu exited during boot: "
                    + bytes(self._console_buf[-2048:]).decode("utf-8",
                                                              "replace"))
            try:
                run_ssh(["ssh", *ssh_args(self.env.sshkey,
                                          self.env.ssh_user, self.ssh_port),
                         f"{self.env.ssh_user}@127.0.0.1", "true"],
                        timeout_s=15)
                return
            except (BootError, subprocess.TimeoutExpired):
                time.sleep(5)
        raise BootError("ssh did not come up during boot")

    # -- instance interface ----------------------------------------------

    def copy(self, host_src: str) -> str:
        dst = "/" + os.path.basename(host_src)
        run_ssh(["scp", *ssh_args(self.env.sshkey, self.env.ssh_user,
                                  self.ssh_port, scp=True),
                 host_src,
                 f"{self.env.ssh_user}@127.0.0.1:{dst}"], timeout_s=180)
        return dst

    def forward(self, port: int) -> str:
        # Reverse-forward a guest port to the host port over ssh -R.
        guest_port = _free_port()
        self._fwd_ports.append((guest_port, port))
        return f"127.0.0.1:{guest_port}"

    def run(self, timeout_s: float, stop: threading.Event,
            command: str) -> OutputStream:
        stream = OutputStream()
        args = ["ssh", *ssh_args(self.env.sshkey, self.env.ssh_user,
                                 self.ssh_port)]
        for guest_port, host_port in self._fwd_ports:
            args += ["-R", f"{guest_port}:127.0.0.1:{host_port}"]
        args += [f"{self.env.ssh_user}@127.0.0.1", command]
        proc = subprocess.Popen(args, stdin=subprocess.DEVNULL,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)

        # Merge the ssh channel and the serial console into one stream
        # (reference: vmimpl merger) — console carries the oopses.  The
        # console pump keeps draining for a grace window after the ssh
        # channel dies: a guest panic kills sshd first while the oops
        # is still flushing over serial.
        ssh_pump = pump_fd(proc.stdout, stream, proc, stop, timeout_s,
                           finish_stream=False)

        def pump_console():
            grace_deadline = None
            while not stop.is_set():
                if proc.poll() is not None and grace_deadline is None:
                    grace_deadline = time.monotonic() + 10.0
                if grace_deadline is not None \
                        and time.monotonic() > grace_deadline:
                    break
                chunk = self._console.get(timeout=0.5)
                if chunk is None:
                    if self._console.finished:
                        break
                    continue
                stream.put(chunk)
            ssh_pump.join()
            stream.finish(stream.error)

        threading.Thread(target=pump_console, daemon=True).start()
        return stream

    def diagnose(self) -> bytes:
        return bytes(self._console_buf[-(128 << 10):])

    def close(self) -> None:
        self._console_stop.set()
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()
            self._proc.wait()


class QemuPool(PoolImpl):
    def __init__(self, env: Env):
        self.env = env
        self._count = int(env.config.get("count", 1))

    def count(self) -> int:
        return self._count

    def create(self, workdir: str, index: int) -> Instance:
        return QemuInstance(workdir, index, self.env)


register_vm_type("qemu", QemuPool)
