"""Cross-cutting utilities: int helpers, hashing, append-only DB,
strict config loading, logging, x86 text generation."""
