"""Content-addressed signatures for corpus keys.

sha1-based Sig with the same usage shape as the reference
(pkg/hash/hash.go:1-57): Hash(data) -> Sig, Sig.String() hex key used
to name corpus records and crash directories.
"""

from __future__ import annotations

import hashlib


class Sig(bytes):
    def string(self) -> str:
        return self.hex()

    def __str__(self) -> str:  # pragma: no cover - trivial
        return self.hex()


def hash_bytes(*chunks: bytes) -> Sig:
    h = hashlib.sha1()
    for c in chunks:
        h.update(c)
    return Sig(h.digest())


def hash_string(*chunks: bytes) -> str:
    return hash_bytes(*chunks).string()
