"""Shared -m32 compile support on a 64-bit host with no 32-bit
libc-dev.

The host glibc HEADERS are i386-correct (they branch on __i386__ /
bits/wordsize.h); only the 32-bit development stub list
(gnu/stubs-32.h, shipped by the 32-bit libc-dev package) is absent,
and on multiarch hosts the asm/ uapi directory hangs under the 64-bit
triplet dir that gcc only adds for the default arch.  Used by
sys/extract (32-bit const extraction) and csource/build (compile-only
gate for 32-bit reproducers).
"""

from __future__ import annotations

import os

#: Contents of the stand-in for the missing 32-bit libc-dev stub list.
#: The stub list only declares which libc functions are unavailable;
#: header-only compiles need none of that information.
STUBS_32_SHIM = ("/* empty: 32-bit libc-dev stubs absent on this "
                 "host; headers-only compile */\n")

MULTIARCH_INCLUDE = "/usr/include/x86_64-linux-gnu"


def m32_compile_flags(shim_dir: str) -> list[str]:
    """cflags for an -m32 header-only compile: writes the empty
    gnu/stubs-32.h stand-in into shim_dir (caller owns the directory
    and its cleanup) and adds the multiarch asm/ include root where
    present (the x86 uapi asm/ headers are width-shared and branch on
    __i386__ internally)."""
    os.makedirs(os.path.join(shim_dir, "gnu"), exist_ok=True)
    stub = os.path.join(shim_dir, "gnu", "stubs-32.h")
    if not os.path.exists(stub):
        with open(stub, "w") as f:
            f.write(STUBS_32_SHIM)
    flags = ["-m32", "-I", shim_dir]
    if os.path.isdir(os.path.join(MULTIARCH_INCLUDE, "asm")):
        flags += ["-I", MULTIARCH_INCLUDE]
    return flags
