"""Windows KD serial-protocol decoder.

Windows kernels talk the KD debugger protocol over the serial port;
to scan a Windows VM console for crashes the raw KD framing has to be
stripped down to the embedded DbgPrint text (reference: pkg/kd/kd.go:4-8
— packet leader scan, type/length/checksum parse, DbgPrint payload
extraction).
"""

from __future__ import annotations

import struct

PACKET_LEADER = b"\x30\x30\x30\x30"  # "0000"
CONTROL_LEADER = b"\x69\x69\x69\x69"  # "iiii"
BREAKIN = 0x62  # 'b'

PACKET_TYPE_KD_DEBUG_IO = 3
DBGKD_PRINT_STRING = 0x3230


def decode(data: bytes) -> tuple[bytes, bytes]:
    """Extract printable DbgPrint text from a KD byte stream.

    Returns (text, remainder) where remainder holds trailing bytes of
    an incomplete packet to be prepended to the next chunk
    (reference: kd.go Decode).
    """
    out = bytearray()
    pos = 0
    n = len(data)
    while pos < n:
        lead = data.find(PACKET_LEADER, pos)
        ctrl = data.find(CONTROL_LEADER, pos)
        if lead == -1 and ctrl == -1:
            # no framing: pass through printable bytes (boot messages
            # are often raw text before KD engages)
            out += bytes(b for b in data[pos:] if b == 0x0A or 32 <= b < 127)
            return bytes(out), b""
        start = min(x for x in (lead, ctrl) if x != -1)
        out += bytes(b for b in data[pos:start]
                     if b == 0x0A or 32 <= b < 127)
        if start + 16 > n:
            return bytes(out), data[start:]
        (ptype, length, _pid, _csum) = struct.unpack_from(
            "<HHII", data, start + 4)
        body_at = start + 16
        if body_at + length + 1 > n:  # +1 trailing 0xAA
            return bytes(out), data[start:]
        body = data[body_at:body_at + length]
        if ptype == PACKET_TYPE_KD_DEBUG_IO and len(body) >= 0x10:
            (api,) = struct.unpack_from("<I", body, 0)
            if api == DBGKD_PRINT_STRING and len(body) >= 0x10:
                (text_len,) = struct.unpack_from("<I", body, 0x0C)
                text = body[0x10:0x10 + text_len]
                out += bytes(b for b in text
                             if b == 0x0A or 32 <= b < 127)
        pos = body_at + length + 1
    return bytes(out), b""
