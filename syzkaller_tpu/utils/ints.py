"""Fixed-width integer helpers (reference: prog/mutation.go:523-611)."""

from __future__ import annotations

MASK64 = (1 << 64) - 1


def mask(width: int) -> int:
    return (1 << (8 * width)) - 1


def swap_int(v: int, width: int) -> int:
    """Byte-swap the low `width` bytes of v."""
    if width == 1:
        return v & 0xFF
    return int.from_bytes((v & mask(width)).to_bytes(width, "little"), "big")


def swap64(v: int) -> int:
    return swap_int(v, 8)


def load_int(data: bytes | bytearray, offset: int, width: int) -> int:
    """Little-endian load (reference: prog/mutation.go:581-595)."""
    return int.from_bytes(data[offset:offset + width], "little")


def store_int(data: bytearray, offset: int, v: int, width: int) -> None:
    """Little-endian store (reference: prog/mutation.go:597-611)."""
    data[offset:offset + width] = (v & mask(width)).to_bytes(width, "little")


def u64(v: int) -> int:
    return v & MASK64


def s64(v: int) -> int:
    v &= MASK64
    return v - (1 << 64) if v >= (1 << 63) else v
