"""Leveled logging with an in-memory cache of recent lines.

The cache exists so that crash/error reports uploaded to the dashboard
can carry the most recent log context (reference: pkg/log/log.go:1-6,
EnableLogCaching used at syz-manager/manager.go:124).
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Optional

_lock = threading.Lock()
_level = 0
_cache: Optional[deque] = None
_cache_max_mem = 0
_cache_mem = 0
_prepend_time = True


def set_level(level: int) -> None:
    global _level
    _level = level


def enable_log_caching(max_lines: int, max_mem: int) -> None:
    """Keep the last max_lines/max_mem of output for error reports
    (reference: pkg/log EnableLogCaching)."""
    global _cache, _cache_max_mem, _cache_mem
    with _lock:
        _cache = deque(maxlen=max_lines)
        _cache_max_mem = max_mem
        _cache_mem = 0


def cached_log_output() -> str:
    with _lock:
        if _cache is None:
            return ""
        return "\n".join(_cache) + "\n" if _cache else ""


def logf(v: int, msg: str, *args) -> None:
    global _cache_mem
    if args:
        msg = msg % args
    line = msg
    if _prepend_time:
        line = time.strftime("%Y/%m/%d %H:%M:%S ") + msg
    with _lock:
        if _cache is not None:
            if _cache.maxlen is not None and len(_cache) == _cache.maxlen:
                _cache_mem -= len(_cache[0])  # about to be evicted
            _cache.append(line)
            _cache_mem += len(line)
            while _cache_mem > _cache_max_mem and len(_cache) > 1:
                _cache_mem -= len(_cache.popleft())
    if v <= _level:
        print(line, file=sys.stderr, flush=True)


def fatalf(msg: str, *args) -> None:
    logf(0, "FATAL: " + msg, *args)
    raise SystemExit(1)
