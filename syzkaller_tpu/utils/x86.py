"""Table-driven x86 instruction model for `text` buffer fuzzing.

The reference drives KVM-guest machine-code fuzzing from a generated
ISA table (reference: pkg/ifuzz/ifuzz.go:14-76 Insn/mode model,
pkg/ifuzz/generated/insns.go generated table, pkg/ifuzz/pseudo.go
hand-written system sequences).  We build the same capability from a
compact declarative opcode-map spec (NASM/SDM-style lines, parsed at
import into Insn records) instead of shipping a 100k-line generated
literal: ~1,900 instructions covering the full one-byte map, the 0F
map with its 66/F3/F2 mandatory-prefix planes (SSE2/SSE3 scalar+
packed), the bare-MMX integer rows and the 3DNow! suffix plane, x87
(memory groups, register families, control ops), SSSE3/SSE4 via
0F38/0F3A with prefixes, AES/SHA/CLMUL, the VMX/SVM virtualization
sets, XSAVE/TSX/CET system state, LOCK-spelled atomics, BMI1/2, the
VEX AVX/AVX2/FMA planes (incl. AVX2 shift-imm groups and VSIB
gathers), AMD XOP/FMA4/TBM, an EVEX AVX-512 plane
(F/BW/DQ promotions + VNNI/IFMA/VBMI/BITALG/VPOPCNTDQ/BF16), and
GFNI/VAES/VPCLMULQDQ in all three encodings — the post-2017 families
are coverage the reference's generated table predates.  Width
variants the reference tables as separate rows (r8/r16/r32/r64,
XSAVE64 vs XSAVE) fold into one row here via the prefix/REX rolls,
except where the 64-bit layout differs (the 48-spelled entries).

Three capabilities mirror the reference API:
  * generate(cfg, r)  - emit one structurally-valid instruction
    (prefixes, REX/VEX, modrm/SIB/disp for 16- and 32/64-bit
    addressing, operand-size-dependent immediates)
  * decode(mode, data) - instruction-length decode against the same
    table (reference: pkg/ifuzz/decode.go) - used by mutation to work
    at instruction granularity and by tests as a round-trip oracle
  * pseudo(mode, r)   - multi-instruction system sequences (MSR
    writes, CR toggles, paging enable, GDT loads, VMX/SVM bringup)
    in the spirit of pkg/ifuzz/pseudo.go

Modes map TextKind: X86_REAL->REAL16, X86_16->PROT16, X86_32->PROT32,
X86_64->LONG64.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

# -- modes (bitmask) ---------------------------------------------------

REAL16, PROT16, PROT32, LONG64 = 1, 2, 4, 8
ALL = REAL16 | PROT16 | PROT32 | LONG64
NO64 = REAL16 | PROT16 | PROT32  # invalid in long mode
X64 = LONG64                     # long mode only

MODE_NAMES = {REAL16: "real16", PROT16: "prot16",
              PROT32: "prot32", LONG64: "long64"}

# -- flags -------------------------------------------------------------

PRIV = 1       # privileged (CPL0 / IOPL): faults in user mode
VEX = 2        # VEX-encoded (AVX)
MEMONLY = 4    # modrm must encode memory (mod != 3)
REGONLY = 8    # modrm must encode a register (mod == 3)
D64 = 16       # default 64-bit operand size in long mode (push/pop/jmp)
EVEX = 32      # EVEX-encoded (AVX-512)
FIXEDENC = 64  # opcode bytes are a complete fixed encoding: emit
               # verbatim, no random prefixes/REX (canonical NOPs,
               # pause) — generation-only rows, decode resolves them
               # through the group/prefix rules

IMM_TOKENS = ("ib", "iw", "id", "iz", "iv", "cb", "cz", "mo")


@dataclass
class Insn:
    name: str
    modes: int
    flags: int
    opcode: bytes          # includes 0F / 0F38 / 0F3A escapes
    vexmap: int = 0        # 0=legacy, 1=0F, 2=0F38, 3=0F3A (VEX)
    plusr: bool = False    # register in low 3 opcode bits
    modrm: bool = False
    reg: int = -1          # /digit for groups, -1 for /r
    imms: tuple = ()
    mprefix: int = 0       # mandatory prefix byte (0x66/0xF3/0xF2)
                           # — VEX specs encode it as the pp field
    suffix: int = -1       # fixed opcode-suffix byte in the ib slot
                           # (3DNow!: 0F 0F modrm <op>), -1 = none

    @property
    def priv(self) -> bool:
        return bool(self.flags & PRIV)


#: SDM mandatory-prefix tokens → prefix byte (pp field for VEX)
_MPREFIX = {"p66": 0x66, "pF3": 0xF3, "pF2": 0xF2}
_PP = {0: 0, 0x66: 1, 0xF3: 2, 0xF2: 3}


def _parse_spec(name: str, enc: str, modes: int, flags: int = 0) -> Insn:
    opcode = bytearray()
    plusr = modrm = False
    reg = -1
    suffix = -1
    imms = []
    vexmap = 0
    mprefix = 0
    for tok in enc.split():
        if tok == "/r":
            modrm = True
        elif len(tok) == 2 and tok[0] == "/" and tok[1].isdigit():
            modrm, reg = True, int(tok[1])
        elif tok == "+r":
            plusr = True
        elif tok in IMM_TOKENS:
            imms.append(tok)
        elif tok == "m":
            flags |= MEMONLY
        elif tok == "rr":
            flags |= REGONLY
        elif tok in _MPREFIX:
            mprefix = _MPREFIX[tok]
        elif len(tok) == 3 and tok[0] == "s":
            # fixed opcode-suffix byte occupying the ib slot (3DNow!)
            suffix = int(tok[1:], 16)
            imms.append("ib")
        elif tok in ("e0F", "e0F38", "e0F3A"):
            flags |= EVEX
            vexmap = {"e0F": 1, "e0F38": 2, "e0F3A": 3}[tok]
        elif tok in ("x08", "x09", "x0A"):
            # AMD XOP: VEX-shaped 3-byte form behind the 8F escape,
            # map_select 8/9/10 (disambiguated from pop_rm by
            # modrm.reg != 0, which mmmm >= 8 guarantees).
            flags |= VEX
            vexmap = {"x08": 8, "x09": 9, "x0A": 10}[tok]
        elif tok.startswith("v"):
            flags |= VEX
            vexmap = {"v0F": 1, "v0F38": 2, "v0F3A": 3}[tok]
        else:
            opcode.append(int(tok, 16))
    return Insn(name, modes, flags, bytes(opcode), vexmap=vexmap,
                plusr=plusr, modrm=modrm, reg=reg, imms=tuple(imms),
                mprefix=mprefix, suffix=suffix)


# -- the opcode-map spec ----------------------------------------------
# (name, encoding, modes[, flags]) - SDM-style notation.  Immediates:
# ib/iw/id fixed; iz = 16/32 by opsize; iv = 16/32/64 by opsize+REX.W;
# cb = rel8; cz = rel16/32; mo = moffs (address-size wide).

_ARITH = ["add", "or", "adc", "sbb", "and", "sub", "xor", "cmp"]

_SPEC: list = []


def _s(name, enc, modes, flags=0):
    _SPEC.append((name, enc, modes, flags))


def _vx(nm: str) -> str:
    """VEX/EVEX dual name of a legacy entry: the _x suffix marks the
    xmm form only where a same-named MMX form exists in the legacy
    maps; V/EVEX encodings have no MMX duals, so the plain name is
    the correct (and reference-matching) spelling."""
    return nm[:-2] if nm.endswith("_x") else nm


# One-byte map: the 8 classic ALU families at 00,08,10,18,20,28,30,38.
for i, op in enumerate(_ARITH):
    base = i * 8
    _s(op, f"{base:02X} /r", ALL)             # r/m8, r8
    _s(op, f"{base + 1:02X} /r", ALL)         # r/m, r
    _s(op, f"{base + 2:02X} /r", ALL)         # r8, r/m8
    _s(op, f"{base + 3:02X} /r", ALL)         # r, r/m
    _s(op, f"{base + 4:02X} ib", ALL)         # al, imm8
    _s(op, f"{base + 5:02X} iz", ALL)         # eax, imm

_s("push_es", "06", NO64)
_s("pop_es", "07", NO64)
_s("push_cs", "0E", NO64)
_s("push_ss", "16", NO64)
_s("pop_ss", "17", NO64)
_s("push_ds", "1E", NO64)
_s("pop_ds", "1F", NO64)
_s("daa", "27", NO64)
_s("das", "2F", NO64)
_s("aaa", "37", NO64)
_s("aas", "3F", NO64)
for r in range(8):  # 40-4F are REX in long mode
    _s("inc", f"{0x40 + r:02X}", NO64)
    _s("dec", f"{0x48 + r:02X}", NO64)
_s("push_r", "50 +r", ALL, D64)
_s("pop_r", "58 +r", ALL, D64)
_s("pusha", "60", NO64)
_s("popa", "61", NO64)
_s("bound", "62 /r m", NO64)
_s("arpl", "63 /r", NO64)
_s("movsxd", "63 /r", X64)
_s("push_iz", "68 iz", ALL, D64)
_s("imul_iz", "69 /r iz", ALL)
_s("push_ib", "6A ib", ALL, D64)
_s("imul_ib", "6B /r ib", ALL)
_s("insb", "6C", ALL, PRIV)
_s("insd", "6D", ALL, PRIV)
_s("outsb", "6E", ALL, PRIV)
_s("outsd", "6F", ALL, PRIV)
_JCC = ["o", "no", "b", "nb", "z", "nz", "be", "nbe",
        "s", "ns", "p", "np", "l", "nl", "le", "nle"]
for i, cc in enumerate(_JCC):
    _s(f"j{cc}", f"{0x70 + i:02X} cb", ALL)
for d, op in enumerate(_ARITH):
    _s(op, f"80 /{d} ib", ALL)
    _s(op, f"81 /{d} iz", ALL)
    _s(op, f"83 /{d} ib", ALL)
_s("test", "84 /r", ALL)
_s("test", "85 /r", ALL)
_s("xchg", "86 /r", ALL)
_s("xchg", "87 /r", ALL)
_s("mov", "88 /r", ALL)
_s("mov", "89 /r", ALL)
_s("mov", "8A /r", ALL)
_s("mov", "8B /r", ALL)
_s("mov_sreg", "8C /r", ALL)
_s("lea", "8D /r m", ALL)
_s("mov_to_sreg", "8E /r", ALL)
_s("pop_rm", "8F /0", ALL, D64)
_s("xchg_ax", "90 +r", ALL)  # 90 = nop
_s("cbw", "98", ALL)
_s("cwd", "99", ALL)
_s("call_far", "9A iz iw", NO64)
_s("fwait", "9B", ALL)
_s("pushf", "9C", ALL, D64)
_s("popf", "9D", ALL, D64)
_s("sahf", "9E", ALL)
_s("lahf", "9F", ALL)
_s("mov_al_moffs", "A0 mo", ALL)
_s("mov_ax_moffs", "A1 mo", ALL)
_s("mov_moffs_al", "A2 mo", ALL)
_s("mov_moffs_ax", "A3 mo", ALL)
_s("movsb", "A4", ALL)
_s("movsd", "A5", ALL)
_s("cmpsb", "A6", ALL)
_s("cmpsd", "A7", ALL)
_s("test_al", "A8 ib", ALL)
_s("test_ax", "A9 iz", ALL)
_s("stosb", "AA", ALL)
_s("stosd", "AB", ALL)
_s("lodsb", "AC", ALL)
_s("lodsd", "AD", ALL)
_s("scasb", "AE", ALL)
_s("scasd", "AF", ALL)
_s("mov_r8_ib", "B0 +r ib", ALL)
_s("mov_r_iv", "B8 +r iv", ALL)
_SHIFT = ["rol", "ror", "rcl", "rcr", "shl", "shr", "sal", "sar"]
for d, op in enumerate(_SHIFT):
    _s(op, f"C0 /{d} ib", ALL)
    _s(op, f"C1 /{d} ib", ALL)
    _s(f"{op}_1", f"D0 /{d}", ALL)
    _s(f"{op}_1", f"D1 /{d}", ALL)
    _s(f"{op}_cl", f"D2 /{d}", ALL)
    _s(f"{op}_cl", f"D3 /{d}", ALL)
_s("ret_iw", "C2 iw", ALL, D64)
_s("ret", "C3", ALL, D64)
_s("les", "C4 /r m", NO64)   # VEX3 escape in 32/64 when mod=11
_s("lds", "C5 /r m", NO64)   # VEX2 escape
_s("mov_rm8_ib", "C6 /0 ib", ALL)
_s("mov_rm_iz", "C7 /0 iz", ALL)
_s("enter", "C8 iw ib", ALL)
_s("leave", "C9", ALL, D64)
_s("retf_iw", "CA iw", ALL)
_s("retf", "CB", ALL)
_s("int3", "CC", ALL)
_s("int_ib", "CD ib", ALL)
_s("into", "CE", NO64)
_s("iret", "CF", ALL)
_s("aam", "D4 ib", NO64)
_s("aad", "D5 ib", NO64)
_s("salc", "D6", NO64)
_s("xlat", "D7", ALL)
_s("loopne", "E0 cb", ALL)
_s("loope", "E1 cb", ALL)
_s("loop", "E2 cb", ALL)
_s("jcxz", "E3 cb", ALL)
_s("in_al_ib", "E4 ib", ALL, PRIV)
_s("in_ax_ib", "E5 ib", ALL, PRIV)
_s("out_ib_al", "E6 ib", ALL, PRIV)
_s("out_ib_ax", "E7 ib", ALL, PRIV)
_s("call", "E8 cz", ALL, D64)
_s("jmp", "E9 cz", ALL, D64)
_s("jmp_far", "EA iz iw", NO64)
_s("jmp_short", "EB cb", ALL)
_s("in_al_dx", "EC", ALL, PRIV)
_s("in_ax_dx", "ED", ALL, PRIV)
_s("out_dx_al", "EE", ALL, PRIV)
_s("out_dx_ax", "EF", ALL, PRIV)
_s("int1", "F1", ALL)
_s("hlt", "F4", ALL, PRIV)
_s("cmc", "F5", ALL)
_s("test_rm8_ib", "F6 /0 ib", ALL)
_s("test_rm8_ib", "F6 /1 ib", ALL)
_s("not", "F6 /2", ALL)
_s("neg", "F6 /3", ALL)
_s("mul", "F6 /4", ALL)
_s("imul", "F6 /5", ALL)
_s("div", "F6 /6", ALL)
_s("idiv", "F6 /7", ALL)
_s("test_rm_iz", "F7 /0 iz", ALL)
_s("test_rm_iz", "F7 /1 iz", ALL)
_s("not", "F7 /2", ALL)
_s("neg", "F7 /3", ALL)
_s("mul", "F7 /4", ALL)
_s("imul", "F7 /5", ALL)
_s("div", "F7 /6", ALL)
_s("idiv", "F7 /7", ALL)
_s("clc", "F8", ALL)
_s("stc", "F9", ALL)
_s("cli", "FA", ALL, PRIV)
_s("sti", "FB", ALL, PRIV)
_s("cld", "FC", ALL)
_s("std", "FD", ALL)
_s("inc_rm8", "FE /0", ALL)
_s("dec_rm8", "FE /1", ALL)
_s("inc_rm", "FF /0", ALL)
_s("dec_rm", "FF /1", ALL)
_s("call_rm", "FF /2", ALL, D64)
_s("call_far_m", "FF /3 m", ALL)
_s("jmp_rm", "FF /4", ALL, D64)
_s("jmp_far_m", "FF /5 m", ALL)
_s("push_rm", "FF /6", ALL, D64)

# 0F map: system + group 6/7.
_s("sldt", "0F 00 /0", ALL)
_s("str", "0F 00 /1", ALL)
_s("lldt", "0F 00 /2", ALL, PRIV)
_s("ltr", "0F 00 /3", ALL, PRIV)
_s("verr", "0F 00 /4", ALL)
_s("verw", "0F 00 /5", ALL)
_s("sgdt", "0F 01 /0 m", ALL)
_s("sidt", "0F 01 /1 m", ALL)
_s("lgdt", "0F 01 /2 m", ALL, PRIV)
_s("lidt", "0F 01 /3 m", ALL, PRIV)
_s("smsw", "0F 01 /4", ALL)
_s("lmsw", "0F 01 /6", ALL, PRIV)
_s("invlpg", "0F 01 /7 m", ALL, PRIV)
# fixed 0F 01 xx encodings (modrm byte is part of the opcode):
_s("vmcall", "0F 01 C1", ALL)
_s("vmlaunch", "0F 01 C2", ALL, PRIV)
_s("vmresume", "0F 01 C3", ALL, PRIV)
_s("vmxoff", "0F 01 C4", ALL, PRIV)
_s("monitor", "0F 01 C8", ALL)
_s("mwait", "0F 01 C9", ALL)
_s("xgetbv", "0F 01 D0", ALL)
_s("xsetbv", "0F 01 D1", ALL, PRIV)
_s("vmrun", "0F 01 D8", ALL, PRIV)
_s("vmmcall", "0F 01 D9", ALL)
_s("vmload", "0F 01 DA", ALL, PRIV)
_s("vmsave", "0F 01 DB", ALL, PRIV)
_s("stgi", "0F 01 DC", ALL, PRIV)
_s("clgi", "0F 01 DD", ALL, PRIV)
_s("skinit", "0F 01 DE", ALL, PRIV)
_s("invlpga", "0F 01 DF", ALL, PRIV)
_s("swapgs", "0F 01 F8", X64, PRIV)
_s("rdtscp", "0F 01 F9", ALL)
_s("lar", "0F 02 /r", ALL)
_s("lsl", "0F 03 /r", ALL)
_s("syscall", "0F 05", X64)
_s("clts", "0F 06", ALL, PRIV)
_s("sysret", "0F 07", X64, PRIV)
_s("invd", "0F 08", ALL, PRIV)
_s("wbinvd", "0F 09", ALL, PRIV)
_s("ud2", "0F 0B", ALL)
_s("prefetch_3dnow", "0F 0D /r m", ALL)
_s("movups", "0F 10 /r", ALL)
_s("movups", "0F 11 /r", ALL)
_s("movlps", "0F 12 /r", ALL)
_s("movlps", "0F 13 /r m", ALL)
_s("unpcklps", "0F 14 /r", ALL)
_s("unpckhps", "0F 15 /r", ALL)
_s("movhps", "0F 16 /r", ALL)
_s("movhps", "0F 17 /r m", ALL)
for d in range(4):
    _s("prefetch", f"0F 18 /{d} m", ALL)
_s("nop_rm", "0F 1F /0", ALL)
_s("mov_from_cr", "0F 20 /r rr", ALL, PRIV)
_s("mov_from_dr", "0F 21 /r rr", ALL, PRIV)
_s("mov_to_cr", "0F 22 /r rr", ALL, PRIV)
_s("mov_to_dr", "0F 23 /r rr", ALL, PRIV)
_s("movaps", "0F 28 /r", ALL)
_s("movaps", "0F 29 /r", ALL)
_s("cvtpi2ps", "0F 2A /r", ALL)
_s("movntps", "0F 2B /r m", ALL)
_s("cvttps2pi", "0F 2C /r", ALL)
_s("cvtps2pi", "0F 2D /r", ALL)
_s("ucomiss", "0F 2E /r", ALL)
_s("comiss", "0F 2F /r", ALL)
_s("wrmsr", "0F 30", ALL, PRIV)
_s("rdtsc", "0F 31", ALL)
_s("rdmsr", "0F 32", ALL, PRIV)
_s("rdpmc", "0F 33", ALL)
_s("sysenter", "0F 34", ALL)
_s("sysexit", "0F 35", ALL, PRIV)
_s("getsec", "0F 37", ALL, PRIV)
for i, cc in enumerate(_JCC):
    _s(f"cmov{cc}", f"0F {0x40 + i:02X} /r", ALL)
_s("movmskps", "0F 50 /r rr", ALL)
_s("sqrtps", "0F 51 /r", ALL)
_s("rsqrtps", "0F 52 /r", ALL)
_s("rcpps", "0F 53 /r", ALL)
_s("andps", "0F 54 /r", ALL)
_s("andnps", "0F 55 /r", ALL)
_s("orps", "0F 56 /r", ALL)
_s("xorps", "0F 57 /r", ALL)
_s("addps", "0F 58 /r", ALL)
_s("mulps", "0F 59 /r", ALL)
_s("cvtps2pd", "0F 5A /r", ALL)
_s("cvtdq2ps", "0F 5B /r", ALL)
_s("subps", "0F 5C /r", ALL)
_s("minps", "0F 5D /r", ALL)
_s("divps", "0F 5E /r", ALL)
_s("maxps", "0F 5F /r", ALL)
# punpck/packss/pcmpgt/packus MMX row (the p66 duals carry the plain
# names in the SSE2 plane below; these are the mm-register forms)
for b, nm in [(0x60, "punpcklbw_mmx"), (0x61, "punpcklwd_mmx"),
              (0x62, "punpckldq_mmx"), (0x63, "packsswb_mmx"),
              (0x64, "pcmpgtb_mmx"), (0x65, "pcmpgtw_mmx"),
              (0x66, "pcmpgtd_mmx"), (0x67, "packuswb_mmx"),
              (0x68, "punpckhbw_mmx"), (0x69, "punpckhwd_mmx"),
              (0x6A, "punpckhdq_mmx"), (0x6B, "packssdw_mmx")]:
    _s(nm, f"0F {b:02X} /r", ALL)
_s("movd", "0F 6E /r", ALL)
_s("movq", "0F 6F /r", ALL)
_s("pshufw", "0F 70 /r ib", ALL)
for d in (2, 4, 6):
    _s("psrlw_i", f"0F 71 /{d} ib rr", ALL)
    _s("psrld_i", f"0F 72 /{d} ib rr", ALL)
    _s("psrlq_i", f"0F 73 /{d} ib rr", ALL)
_s("pcmpeqb", "0F 74 /r", ALL)
_s("pcmpeqw", "0F 75 /r", ALL)
_s("pcmpeqd", "0F 76 /r", ALL)
_s("emms", "0F 77", ALL)
_s("vmread", "0F 78 /r", ALL, PRIV)
_s("vmwrite", "0F 79 /r", ALL, PRIV)
_s("movd", "0F 7E /r", ALL)
_s("movq", "0F 7F /r", ALL)
for i, cc in enumerate(_JCC):
    _s(f"j{cc}_near", f"0F {0x80 + i:02X} cz", ALL)
for i, cc in enumerate(_JCC):
    _s(f"set{cc}", f"0F {0x90 + i:02X} /r", ALL)
_s("push_fs", "0F A0", ALL, D64)
_s("pop_fs", "0F A1", ALL, D64)
_s("cpuid", "0F A2", ALL)
_s("bt", "0F A3 /r", ALL)
_s("shld_ib", "0F A4 /r ib", ALL)
_s("shld_cl", "0F A5 /r", ALL)
_s("push_gs", "0F A8", ALL, D64)
_s("pop_gs", "0F A9", ALL, D64)
_s("rsm", "0F AA", ALL, PRIV)
_s("bts", "0F AB /r", ALL)
_s("shrd_ib", "0F AC /r ib", ALL)
_s("shrd_cl", "0F AD /r", ALL)
_s("fxsave", "0F AE /0 m", ALL)
_s("fxrstor", "0F AE /1 m", ALL)
_s("ldmxcsr", "0F AE /2 m", ALL)
_s("stmxcsr", "0F AE /3 m", ALL)
_s("xsave", "0F AE /4 m", ALL)
_s("xrstor", "0F AE /5 m", ALL)
_s("clflush", "0F AE /7 m", ALL)
_s("lfence", "0F AE E8", ALL)
_s("mfence", "0F AE F0", ALL)
_s("sfence", "0F AE F8", ALL)
_s("imul_r_rm", "0F AF /r", ALL)
_s("cmpxchg", "0F B0 /r", ALL)
_s("cmpxchg", "0F B1 /r", ALL)
_s("lss", "0F B2 /r m", ALL)
_s("btr", "0F B3 /r", ALL)
_s("lfs", "0F B4 /r m", ALL)
_s("lgs", "0F B5 /r m", ALL)
_s("movzx_b", "0F B6 /r", ALL)
_s("movzx_w", "0F B7 /r", ALL)
_s("ud1", "0F B9 /r", ALL)
_s("bt_i", "0F BA /4 ib", ALL)
_s("bts_i", "0F BA /5 ib", ALL)
_s("btr_i", "0F BA /6 ib", ALL)
_s("btc_i", "0F BA /7 ib", ALL)
_s("btc", "0F BB /r", ALL)
_s("bsf", "0F BC /r", ALL)
_s("bsr", "0F BD /r", ALL)
_s("movsx_b", "0F BE /r", ALL)
_s("movsx_w", "0F BF /r", ALL)
_s("xadd", "0F C0 /r", ALL)
_s("xadd", "0F C1 /r", ALL)
_s("cmpps", "0F C2 /r ib", ALL)
_s("movnti", "0F C3 /r m", ALL)
_s("pinsrw", "0F C4 /r ib", ALL)
_s("pextrw", "0F C5 /r ib rr", ALL)
_s("shufps", "0F C6 /r ib", ALL)
_s("cmpxchg8b", "0F C7 /1 m", ALL)
_s("bswap", "0F C8 +r", ALL)
# MMX arithmetic rows D1-FE: same opcode positions as the 66-prefixed
# SSE2 plane below, operating on mm registers (SDM table A-3, no-pfx
# column).  These carry the reference's plain names; the xmm duals
# keep their _x suffix.
for b, nm in [(0xD1, "psrlw"), (0xD2, "psrld"), (0xD3, "psrlq"),
              (0xD4, "paddq"), (0xD5, "pmullw"),
              (0xD8, "psubusb"), (0xD9, "psubusw"), (0xDA, "pminub"),
              (0xDB, "pand"), (0xDC, "paddusb"), (0xDD, "paddusw"),
              (0xDE, "pmaxub"), (0xDF, "pandn"),
              (0xE0, "pavgb"), (0xE1, "psraw"), (0xE2, "psrad"),
              (0xE3, "pavgw"), (0xE4, "pmulhuw"), (0xE5, "pmulhw"),
              (0xE8, "psubsb"), (0xE9, "psubsw"), (0xEA, "pminsw"),
              (0xEB, "por"), (0xEC, "paddsb"), (0xED, "paddsw"),
              (0xEE, "pmaxsw"), (0xEF, "pxor"),
              (0xF1, "psllw"), (0xF2, "pslld"), (0xF3, "psllq"),
              (0xF4, "pmuludq"), (0xF5, "pmaddwd"), (0xF6, "psadbw"),
              (0xF8, "psubb"), (0xF9, "psubw"), (0xFA, "psubd"),
              (0xFB, "psubq"), (0xFC, "paddb"), (0xFD, "paddw"),
              (0xFE, "paddd")]:
    _s(nm, f"0F {b:02X} /r", ALL)
_s("movntq", "0F E7 /r m", ALL)
_s("maskmovq", "0F F7 /r rr", ALL)
_s("pmovmskb", "0F D7 /r rr", ALL)

# 0F38 / 0F3A maps (SSSE3/SSE4 subset; all take modrm).
for b, nm in [(0x00, "pshufb"), (0x01, "phaddw"), (0x02, "phaddd"),
              (0x03, "phaddsw"), (0x04, "pmaddubsw"), (0x05, "phsubw"),
              (0x06, "phsubd"), (0x07, "phsubsw"), (0x08, "psignb"),
              (0x09, "psignw"), (0x0A, "psignd"), (0x0B, "pmulhrsw"),
              (0x1C, "pabsb"), (0x1D, "pabsw"), (0x1E, "pabsd"),
              (0xF0, "movbe"), (0xF1, "movbe")]:
    _s(nm, f"0F 38 {b:02X} /r", ALL)
for b, nm in [(0x08, "roundps"), (0x09, "roundpd"), (0x0A, "roundss"),
              (0x0B, "roundsd"), (0x0C, "blendps"), (0x0D, "blendpd"),
              (0x0E, "pblendw"), (0x0F, "palignr"), (0x14, "pextrb"),
              (0x15, "pextrw2"), (0x16, "pextrd"), (0x17, "extractps"),
              (0x20, "pinsrb"), (0x21, "insertps"), (0x22, "pinsrd"),
              (0x42, "mpsadbw"), (0x60, "pcmpestrm"),
              (0x61, "pcmpestri"), (0x62, "pcmpistrm"),
              (0x63, "pcmpistri")]:
    _s(nm, f"0F 3A {b:02X} /r ib", ALL)

# VEX-encoded AVX forms (32/64-bit modes; C4/C5 escape).
_VEXM = PROT32 | LONG64
for b, nm in [(0x10, "vmovups"), (0x11, "vmovups"), (0x14, "vunpcklps"),
              (0x28, "vmovaps"), (0x29, "vmovaps"), (0x51, "vsqrtps"),
              (0x54, "vandps"), (0x57, "vxorps"), (0x58, "vaddps"),
              (0x59, "vmulps"), (0x5C, "vsubps"), (0x5E, "vdivps"),
              (0x6F, "vmovdqa"), (0x74, "vpcmpeqb"), (0x76, "vpcmpeqd"),
              (0xEF, "vpxor"), (0xFE, "vpaddd")]:
    _s(nm, f"v0F {b:02X} /r", _VEXM)
for b, nm in [(0x00, "vpshufb"), (0x17, "vptest"), (0x18, "vbroadcastss"),
              (0x29, "vpcmpeqq"), (0x40, "vpmulld")]:
    _s(nm, f"v0F38 {b:02X} /r", _VEXM)
for b, nm in [(0x0F, "vpalignr"), (0x4A, "vblendvps"), (0x18, "vinsertf128"),
              (0x19, "vextractf128")]:
    _s(nm, f"v0F3A {b:02X} /r ib", _VEXM)

# ---- r5 expansion: mandatory-prefix SSE planes, x87, wide VEX -------
# (SDM volume 2 opcode maps; the p66/pF3/pF2 tokens are the mandatory
# prefixes, riding the VEX.pp field for v-forms.)

# 66-prefixed 0F map: the packed-double + integer-SSE2 plane.
_SSE2_66_0F = [
    (0x10, "movupd"), (0x11, "movupd"), (0x12, "movlpd_m"), (0x13, "movlpd_m"),
    (0x14, "unpcklpd"), (0x15, "unpckhpd"), (0x16, "movhpd_m"),
    (0x17, "movhpd_m"), (0x28, "movapd"), (0x29, "movapd"),
    (0x2A, "cvtpi2pd"), (0x2B, "movntpd"), (0x2C, "cvttpd2pi"),
    (0x2D, "cvtpd2pi"), (0x2E, "ucomisd"), (0x2F, "comisd"),
    (0x51, "sqrtpd"), (0x54, "andpd"), (0x55, "andnpd"), (0x56, "orpd"),
    (0x57, "xorpd"), (0x58, "addpd"), (0x59, "mulpd"),
    (0x5A, "cvtpd2ps"), (0x5B, "cvtps2dq"), (0x5C, "subpd"),
    (0x5D, "minpd"), (0x5E, "divpd"), (0x5F, "maxpd"),
    (0x60, "punpcklbw"), (0x61, "punpcklwd"), (0x62, "punpckldq"),
    (0x63, "packsswb"), (0x64, "pcmpgtb"), (0x65, "pcmpgtw"),
    (0x66, "pcmpgtd"), (0x67, "packuswb"), (0x68, "punpckhbw"),
    (0x69, "punpckhwd"), (0x6A, "punpckhdq"), (0x6B, "packssdw"),
    (0x6C, "punpcklqdq"), (0x6D, "punpckhqdq"), (0x6E, "movd_x"),
    (0x6F, "movdqa"), (0x74, "pcmpeqb"), (0x75, "pcmpeqw"),
    (0x76, "pcmpeqd"), (0x7C, "haddpd"), (0x7D, "hsubpd"),
    (0x7E, "movd_x"), (0x7F, "movdqa"), (0xD0, "addsubpd"),
    (0xD1, "psrlw_x"), (0xD2, "psrld_x"), (0xD3, "psrlq_x"),
    (0xD4, "paddq_x"), (0xD5, "pmullw_x"), (0xD8, "psubusb_x"),
    (0xD9, "psubusw_x"), (0xDA, "pminub_x"), (0xDB, "pand_x"),
    (0xDC, "paddusb_x"), (0xDD, "paddusw_x"), (0xDE, "pmaxub_x"),
    (0xDF, "pandn_x"), (0xE0, "pavgb_x"), (0xE1, "psraw_x"),
    (0xE2, "psrad_x"), (0xE3, "pavgw_x"), (0xE4, "pmulhuw_x"),
    (0xE5, "pmulhw_x"), (0xE6, "cvttpd2dq"), (0xE7, "movntdq"),
    (0xE8, "psubsb_x"), (0xE9, "psubsw_x"), (0xEA, "pminsw_x"),
    (0xEB, "por_x"), (0xEC, "paddsb_x"), (0xED, "paddsw_x"),
    (0xEE, "pmaxsw_x"), (0xEF, "pxor_x"), (0xF1, "psllw_x"),
    (0xF2, "pslld_x"), (0xF3, "psllq_x"), (0xF4, "pmuludq_x"),
    (0xF5, "pmaddwd_x"), (0xF6, "psadbw_x"), (0xF8, "psubb_x"),
    (0xF9, "psubw_x"), (0xFA, "psubd_x"), (0xFB, "psubq_x"),
    (0xFC, "paddb_x"), (0xFD, "paddw_x"), (0xFE, "paddd_x"),
]
_SSE2_MEMONLY = {"movlpd_m", "movhpd_m", "movntpd", "movntdq"}
for b, nm in _SSE2_66_0F:
    suffix = " m" if nm in _SSE2_MEMONLY else ""
    _s(nm, f"p66 0F {b:02X} /r{suffix}", ALL)
_s("movmskpd", "p66 0F 50 /r rr", ALL)
_s("pshufd", "p66 0F 70 /r ib", ALL)
for grp, ops in ((0x71, (2, 4, 6)), (0x72, (2, 4, 6)), (0x73, (2, 3, 6, 7))):
    for d in ops:
        _s(f"pshift_{grp:02X}_{d}", f"p66 0F {grp:02X} /{d} rr ib", ALL)
_s("cmppd", "p66 0F C2 /r ib", ALL)
_s("pinsrw_x", "p66 0F C4 /r ib", ALL)
_s("pextrw_x", "p66 0F C5 /r rr ib", ALL)
_s("shufpd", "p66 0F C6 /r ib", ALL)
_s("movq_x", "p66 0F D6 /r", ALL)
_s("pmovmskb_x", "p66 0F D7 /r rr", ALL)

# F3-prefixed 0F map: scalar-single + misc.
_SSE_F3_0F = [
    (0x10, "movss"), (0x11, "movss"), (0x12, "movsldup"),
    (0x16, "movshdup"), (0x2A, "cvtsi2ss"), (0x2C, "cvttss2si"),
    (0x2D, "cvtss2si"), (0x51, "sqrtss"), (0x52, "rsqrtss"),
    (0x53, "rcpss"), (0x58, "addss"), (0x59, "mulss"),
    (0x5A, "cvtss2sd"), (0x5B, "cvttps2dq"), (0x5C, "subss"),
    (0x5D, "minss"), (0x5E, "divss"), (0x5F, "maxss"),
    (0x6F, "movdqu"), (0x7E, "movq_f3"), (0x7F, "movdqu"),
    (0xB8, "popcnt"), (0xBC, "tzcnt"), (0xBD, "lzcnt"),
    (0xE6, "cvtdq2pd"),
]
for b, nm in _SSE_F3_0F:
    _s(nm, f"pF3 0F {b:02X} /r", ALL)
_s("pshufhw", "pF3 0F 70 /r ib", ALL)
_s("cmpss", "pF3 0F C2 /r ib", ALL)
_s("movq2dq", "pF3 0F D6 /r rr", ALL)

# F2-prefixed 0F map: scalar-double + misc.
_SSE_F2_0F = [
    (0x10, "movsd_x"), (0x11, "movsd_x"), (0x12, "movddup"),
    (0x2A, "cvtsi2sd"), (0x2C, "cvttsd2si"), (0x2D, "cvtsd2si"),
    (0x51, "sqrtsd"), (0x58, "addsd"), (0x59, "mulsd"),
    (0x5A, "cvtsd2ss"), (0x5C, "subsd"), (0x5D, "minsd"),
    (0x5E, "divsd"), (0x5F, "maxsd"), (0x7C, "haddps"),
    (0x7D, "hsubps"), (0xD0, "addsubps"), (0xE6, "cvtpd2dq"),
]
for b, nm in _SSE_F2_0F:
    _s(nm, f"pF2 0F {b:02X} /r", ALL)
_s("pshuflw", "pF2 0F 70 /r ib", ALL)
_s("cmpsd_x", "pF2 0F C2 /r ib", ALL)
_s("movdq2q", "pF2 0F D6 /r rr", ALL)
_s("lddqu", "pF2 0F F0 /r m", ALL)

# legacy 0F leftovers: bswap + the reserved hint-nop block
for b in range(0x19, 0x1F):
    _s("hint_nop", f"0F {b:02X} /r", ALL)
# CET end-branch markers (F3 0F 1E FA/FB fixed forms)
_s("endbr64", "pF3 0F 1E FB", ALL)
_s("endbr32", "pF3 0F 1E FA", ALL)

# fsgsbase group (F3 0F AE /0-/3, long mode only)
for d, nm in ((0, "rdfsbase"), (1, "rdgsbase"), (2, "wrfsbase"),
              (3, "wrgsbase")):
    _s(nm, f"pF3 0F AE /{d} rr", X64)

# 66 0F38: SSSE3/SSE4 xmm plane (the no-prefix forms are the MMX duals
# already in the table) + AES-NI + adcx/adox + F2 crc32.
_SSE4_66_0F38 = [
    (0x00, "pshufb_x"), (0x01, "phaddw_x"), (0x02, "phaddd_x"),
    (0x03, "phaddsw_x"), (0x04, "pmaddubsw_x"), (0x05, "phsubw_x"),
    (0x06, "phsubd_x"), (0x07, "phsubsw_x"), (0x08, "psignb_x"),
    (0x09, "psignw_x"), (0x0A, "psignd_x"), (0x0B, "pmulhrsw_x"),
    (0x10, "pblendvb"), (0x14, "blendvps"), (0x15, "blendvpd"),
    (0x17, "ptest"), (0x1C, "pabsb_x"), (0x1D, "pabsw_x"),
    (0x1E, "pabsd_x"), (0x20, "pmovsxbw"), (0x21, "pmovsxbd"),
    (0x22, "pmovsxbq"), (0x23, "pmovsxwd"), (0x24, "pmovsxwq"),
    (0x25, "pmovsxdq"), (0x28, "pmuldq"), (0x29, "pcmpeqq"),
    (0x2B, "packusdw"), (0x30, "pmovzxbw"), (0x31, "pmovzxbd"),
    (0x32, "pmovzxbq"), (0x33, "pmovzxwd"), (0x34, "pmovzxwq"),
    (0x35, "pmovzxdq"), (0x37, "pcmpgtq"), (0x38, "pminsb"),
    (0x39, "pminsd"), (0x3A, "pminuw"), (0x3B, "pminud"),
    (0x3C, "pmaxsb"), (0x3D, "pmaxsd"), (0x3E, "pmaxuw"),
    (0x3F, "pmaxud"), (0x40, "pmulld"), (0x41, "phminposuw"),
    (0xDB, "aesimc"), (0xDC, "aesenc"), (0xDD, "aesenclast"),
    (0xDE, "aesdec"), (0xDF, "aesdeclast"), (0xF6, "adcx"),
]
for b, nm in _SSE4_66_0F38:
    _s(nm, f"p66 0F 38 {b:02X} /r", ALL)
_s("movntdqa", "p66 0F 38 2A /r m", ALL)
_s("adox", "pF3 0F 38 F6 /r", ALL)
_s("crc32_8", "pF2 0F 38 F0 /r", ALL)
_s("crc32", "pF2 0F 38 F1 /r", ALL)

# 66 0F3A: SSE4 immediates + PCLMUL + AES keygen.
_SSE4_66_0F3A = [
    (0x08, "roundps"), (0x09, "roundpd"), (0x0A, "roundss"),
    (0x0B, "roundsd"), (0x0C, "blendps"), (0x0D, "blendpd"),
    (0x0E, "pblendw"), (0x0F, "palignr_x"), (0x14, "pextrb"),
    (0x15, "pextrw_sse4"), (0x16, "pextrd"), (0x17, "extractps"),
    (0x20, "pinsrb"), (0x21, "insertps"), (0x22, "pinsrd"),
    (0x40, "dpps"), (0x41, "dppd"), (0x42, "mpsadbw"),
    (0x44, "pclmulqdq"), (0x60, "pcmpestrm"), (0x61, "pcmpestri"),
    (0x62, "pcmpistrm"), (0x63, "pcmpistri"), (0xDF, "aeskeygenassist"),
]
for b, nm in _SSE4_66_0F3A:
    _s(nm, f"p66 0F 3A {b:02X} /r ib", ALL)

# x87: the eight escape bytes as full modrm groups (mem forms) — the
# register encodings (mod=3) flow through the same group for decode
# lengths; the named reg families below are generation-side spellings.
_X87_GROUPS = {
    0xD8: ["fadd", "fmul", "fcom", "fcomp", "fsub", "fsubr", "fdiv",
           "fdivr"],
    0xD9: ["fld", "fxch_g", "fst", "fstp", "fldenv", "fldcw",
           "fnstenv", "fnstcw"],
    0xDA: ["fiadd", "fimul", "ficom", "ficomp", "fisub", "fisubr",
           "fidiv", "fidivr"],
    0xDB: ["fild", "fisttp", "fist", "fistp", "fcmov_g", "fld80",
           "fucomi_g", "fstp80"],
    0xDC: ["fadd64", "fmul64", "fcom64", "fcomp64", "fsub64",
           "fsubr64", "fdiv64", "fdivr64"],
    0xDD: ["fld64", "fisttp64", "fst64", "fstp64", "frstor",
           "fucomp_g", "fnsave", "fnstsw"],
    0xDE: ["fiadd16", "fimul16", "ficom16", "ficomp16", "fisub16",
           "fisubr16", "fidiv16", "fidivr16"],
    0xDF: ["fild16", "fisttp16", "fist16", "fistp16", "fbld",
           "fild64", "fbstp", "fistp64"],
}
for esc, names in _X87_GROUPS.items():
    for d, nm in enumerate(names):
        _s(nm, f"{esc:02X} /{d}", ALL)
# named register families (+i on st(i)) and fixed control ops
for enc, nm in [("D8 C0", "fadd_st"), ("D8 C8", "fmul_st"),
                ("D8 D0", "fcom_st"), ("D8 D8", "fcomp_st"),
                ("D8 E0", "fsub_st"), ("D8 E8", "fsubr_st"),
                ("D8 F0", "fdiv_st"), ("D8 F8", "fdivr_st"),
                ("D9 C0", "fld_st"), ("D9 C8", "fxch"),
                ("DA C0", "fcmovb"), ("DA C8", "fcmove"),
                ("DA D0", "fcmovbe"), ("DA D8", "fcmovu"),
                ("DB C0", "fcmovnb"), ("DB C8", "fcmovne"),
                ("DB D0", "fcmovnbe"), ("DB D8", "fcmovnu"),
                ("DB E8", "fucomi"), ("DB F0", "fcomi"),
                ("DC C0", "fadd_to"), ("DC C8", "fmul_to"),
                ("DC E0", "fsubr_to"), ("DC E8", "fsub_to"),
                ("DC F0", "fdivr_to"), ("DC F8", "fdiv_to"),
                ("DD C0", "ffree"), ("DD D0", "fst_st"),
                ("DD D8", "fstp_st"), ("DD E0", "fucom"),
                ("DD E8", "fucomp"), ("DE C0", "faddp"),
                ("DE C8", "fmulp"), ("DE E0", "fsubrp"),
                ("DE E8", "fsubp"), ("DE F0", "fdivrp"),
                ("DE F8", "fdivp"), ("DF E8", "fucomip"),
                ("DF F0", "fcomip")]:
    _s(nm, f"{enc} +r", ALL)
for enc, nm in [("D9 D0", "fnop"), ("D9 E0", "fchs"), ("D9 E1", "fabs"),
                ("D9 E4", "ftst"), ("D9 E5", "fxam"), ("D9 E8", "fld1"),
                ("D9 E9", "fldl2t"), ("D9 EA", "fldl2e"),
                ("D9 EB", "fldpi"), ("D9 EC", "fldlg2"),
                ("D9 ED", "fldln2"), ("D9 EE", "fldz"),
                ("D9 F0", "f2xm1"), ("D9 F1", "fyl2x"),
                ("D9 F2", "fptan"), ("D9 F3", "fpatan"),
                ("D9 F4", "fxtract"), ("D9 F5", "fprem1"),
                ("D9 F6", "fdecstp"), ("D9 F7", "fincstp"),
                ("D9 F8", "fprem"), ("D9 F9", "fyl2xp1"),
                ("D9 FA", "fsqrt"), ("D9 FB", "fsincos"),
                ("D9 FC", "frndint"), ("D9 FD", "fscale"),
                ("D9 FE", "fsin"), ("D9 FF", "fcos"),
                ("DA E9", "fucompp"), ("DB E2", "fnclex"),
                ("DB E3", "fninit"), ("DE D9", "fcompp"),
                ("DF E0", "fnstsw_ax")]:
    _s(nm, f"{enc}", ALL)

# ---- VEX planes with pp ---------------------------------------------

# v66 0F: AVX duals of the whole 66-prefixed SSE2 plane (AVX/AVX2).
for b, nm in _SSE2_66_0F:
    suffix = " m" if nm in _SSE2_MEMONLY else ""
    _s(f"v{_vx(nm)}", f"v0F p66 {b:02X} /r{suffix}", _VEXM)
_s("vmovmskpd", "v0F p66 50 /r rr", _VEXM)
_s("vpshufd", "v0F p66 70 /r ib", _VEXM)
_s("vcmppd", "v0F p66 C2 /r ib", _VEXM)
_s("vpinsrw", "v0F p66 C4 /r ib", _VEXM)
_s("vpextrw", "v0F p66 C5 /r rr ib", _VEXM)
_s("vshufpd", "v0F p66 C6 /r ib", _VEXM)
_s("vpmovmskb", "v0F p66 D7 /r rr", _VEXM)

# vF3/vF2 0F scalar planes.
for b, nm in _SSE_F3_0F:
    if nm in ("popcnt", "tzcnt", "lzcnt"):
        continue
    _s(f"v{_vx(nm)}", f"v0F pF3 {b:02X} /r", _VEXM)
for b, nm in _SSE_F2_0F:
    _s(f"v{_vx(nm)}", f"v0F pF2 {b:02X} /r", _VEXM)
_s("vcmpss", "v0F pF3 C2 /r ib", _VEXM)
_s("vcmpsd", "v0F pF2 C2 /r ib", _VEXM)
_s("vpshufhw", "v0F pF3 70 /r ib", _VEXM)
_s("vpshuflw", "v0F pF2 70 /r ib", _VEXM)
_s("vlddqu", "v0F pF2 F0 /r m", _VEXM)

# v0F no-pp gaps (packed-single plane beyond the r4 seed set).
for b, nm in [(0x12, "vmovlps"), (0x13, "vmovlps_st"),
              (0x15, "vunpckhps"), (0x16, "vmovhps"),
              (0x17, "vmovhps_st"), (0x2E, "vucomiss"),
              (0x2F, "vcomiss"), (0x50, "vmovmskps"),
              (0x52, "vrsqrtps"), (0x53, "vrcpps"), (0x55, "vandnps"),
              (0x56, "vorps"), (0x5A, "vcvtps2pd"),
              (0x5B, "vcvtdq2ps"), (0x5D, "vminps"), (0x5F, "vmaxps")]:
    _s(nm, f"v0F {b:02X} /r", _VEXM)
_s("vcmpps", "v0F C2 /r ib", _VEXM)
_s("vshufps", "v0F C6 /r ib", _VEXM)

# v66 0F38: SSE4 duals + AVX2 integer extensions + gathers + FMA.
for b, nm in _SSE4_66_0F38:
    if nm == "adcx":
        continue
    _s(f"v{_vx(nm)}", f"v0F38 p66 {b:02X} /r", _VEXM)
for b, nm in [(0x0C, "vpermilps"), (0x0D, "vpermilpd"),
              (0x0E, "vtestps"), (0x0F, "vtestpd"),
              (0x13, "vcvtph2ps"), (0x16, "vpermps"), (0x18, "vbroadcastss_x"),
              (0x19, "vbroadcastsd"), (0x1A, "vbroadcastf128"),
              (0x2C, "vmaskmovps"), (0x2D, "vmaskmovpd"),
              (0x36, "vpermd"), (0x45, "vpsrlvd"), (0x46, "vpsravd"),
              (0x47, "vpsllvd"), (0x58, "vpbroadcastd"),
              (0x59, "vpbroadcastq"), (0x5A, "vbroadcasti128"),
              (0x78, "vpbroadcastb"), (0x79, "vpbroadcastw"),
              (0x8C, "vpmaskmovd"), (0x8E, "vpmaskmovd_st")]:
    _s(nm, f"v0F38 p66 {b:02X} /r", _VEXM)
for b, nm in [(0x90, "vpgatherdd"), (0x91, "vpgatherqd"),
              (0x92, "vgatherdps"), (0x93, "vgatherqps")]:
    _s(nm, f"v0F38 p66 {b:02X} /r m", _VEXM)  # VSIB: memory-only
# FMA3: three accumulation orders x {packed, scalar}; VEX.W picks
# s/d within an entry, so each opcode is one table row.
_FMA3 = {0x96: "vfmaddsub132ps", 0x97: "vfmsubadd132ps",
         0x98: "vfmadd132ps", 0x99: "vfmadd132ss",
         0x9A: "vfmsub132ps", 0x9B: "vfmsub132ss",
         0x9C: "vfnmadd132ps", 0x9D: "vfnmadd132ss",
         0x9E: "vfnmsub132ps", 0x9F: "vfnmsub132ss"}
for base, nm in _FMA3.items():
    _s(nm, f"v0F38 p66 {base:02X} /r", _VEXM)
    _s(nm.replace("132", "213"), f"v0F38 p66 {base + 0x10:02X} /r",
       _VEXM)
    _s(nm.replace("132", "231"), f"v0F38 p66 {base + 0x20:02X} /r",
       _VEXM)
# AVX2 shift-by-immediate groups (VEX duals of the p66 0F 71-73
# groups; vvvv carries the destination).
for grp, ops in ((0x71, ((2, "vpsrlw_i"), (4, "vpsraw_i"),
                         (6, "vpsllw_i"))),
                 (0x72, ((2, "vpsrld_i"), (4, "vpsrad_i"),
                         (6, "vpslld_i"))),
                 (0x73, ((2, "vpsrlq_i"), (3, "vpsrldq_i"),
                         (6, "vpsllq_i"), (7, "vpslldq_i")))):
    for d, nm in ops:
        _s(nm, f"v0F p66 {grp:02X} /{d} rr ib", _VEXM)
_s("vmaskmovdqu", "v0F p66 F7 /r rr", _VEXM)
_s("vmovntdq", "v0F p66 E7 /r m", _VEXM)
_s("vmovntpd", "v0F p66 2B /r m", _VEXM)
_s("vmovntps", "v0F 2B /r m", _VEXM)
_s("vzeroupper", "v0F 77", _VEXM)   # VEX.L picks vzeroall; one row
_s("vldmxcsr", "v0F AE /2 m", _VEXM)
_s("vstmxcsr", "v0F AE /3 m", _VEXM)

# BMI1/BMI2 (VEX-encoded GPR ops).
_s("andn", "v0F38 F2 /r", _VEXM)
_s("blsr", "v0F38 F3 /1 rr", _VEXM)
_s("blsmsk", "v0F38 F3 /2 rr", _VEXM)
_s("blsi", "v0F38 F3 /3 rr", _VEXM)
_s("bzhi", "v0F38 F5 /r", _VEXM)
_s("pext", "v0F38 pF3 F5 /r", _VEXM)
_s("pdep", "v0F38 pF2 F5 /r", _VEXM)
_s("mulx", "v0F38 pF2 F6 /r", _VEXM)
_s("bextr", "v0F38 F7 /r", _VEXM)
_s("shlx", "v0F38 p66 F7 /r", _VEXM)
_s("sarx", "v0F38 pF3 F7 /r", _VEXM)
_s("shrx", "v0F38 pF2 F7 /r", _VEXM)

# v66 0F3A: immediates plane + AVX2 + F16C + RORX.
for b, nm in _SSE4_66_0F3A:
    _s(f"v{_vx(nm)}", f"v0F3A p66 {b:02X} /r ib", _VEXM)
for b, nm in [(0x00, "vpermq"), (0x01, "vpermpd"), (0x02, "vpblendd"),
              (0x04, "vpermilps_i"), (0x05, "vpermilpd_i"),
              (0x06, "vperm2f128"), (0x1D, "vcvtps2ph"),
              (0x38, "vinserti128"), (0x39, "vextracti128"),
              (0x46, "vperm2i128"), (0x4B, "vblendvpd"),
              (0x4C, "vpblendvb")]:
    _s(nm, f"v0F3A p66 {b:02X} /r ib", _VEXM)
_s("rorx", "v0F3A pF2 F0 /r ib", _VEXM)

# ---- EVEX plane (AVX-512 foundation) --------------------------------
# The AVX-512 promotions of the SSE2/scalar/FMA planes plus the
# 512-native permute/compress/ternlog family.  Length rule: the EVEX
# payload is always 3 bytes after 62; disp8 compression rescales the
# displacement VALUE, not its size, so decode shares the VEX logic.

for b, nm in _SSE2_66_0F:
    suffix = " m" if nm in _SSE2_MEMONLY else ""
    _s(f"ev_{_vx(nm)}", f"e0F p66 {b:02X} /r{suffix}", _VEXM)
for b, nm in _SSE_F3_0F:
    if nm in ("popcnt", "tzcnt", "lzcnt"):
        continue
    _s(f"ev_{_vx(nm)}", f"e0F pF3 {b:02X} /r", _VEXM)
for b, nm in _SSE_F2_0F:
    _s(f"ev_{_vx(nm)}", f"e0F pF2 {b:02X} /r", _VEXM)
for base in (0x96, 0x98, 0x9A, 0x9C, 0x9E, 0xA6, 0xA8, 0xAA, 0xAC,
             0xAE, 0xB6, 0xB8, 0xBA, 0xBC, 0xBE):
    _s(f"ev_fma_{base:02X}", f"e0F38 p66 {base:02X} /r", _VEXM)
    _s(f"ev_fma_{base + 1:02X}", f"e0F38 p66 {base + 1:02X} /r", _VEXM)
for b, nm in [(0x16, "evpermps"), (0x1F, "evpabsq"), (0x36, "evpermd"),
              (0x64, "evpblendmd"), (0x65, "evblendmps"),
              (0x75, "evpermi2w"), (0x76, "evpermi2d"),
              (0x77, "evpermi2ps"), (0x7D, "evpermt2w"),
              (0x7E, "evpermt2d"), (0x7F, "evpermt2ps"),
              (0x88, "evexpandps"), (0x89, "evpexpandd"),
              (0x8A, "evcompressps"), (0x8B, "evpcompressd"),
              (0xC4, "evpconflictd"), (0xC8, "evexp2ps_er"),
              (0xCA, "evrcp28ps"), (0xCC, "evrsqrt28ps")]:
    _s(nm, f"e0F38 p66 {b:02X} /r", _VEXM)
# EVEX promotions of the 66 0F38 integer plane (AVX-512F/BW/DQ
# subset with a 1:1 legacy dual; blendv/ptest got replaced by
# mask-register ops, and the SSSE3 horizontal/sign family plus
# phminposuw were never promoted — all deliberately absent).
_NO_EVEX_0F38 = {"pblendvb", "blendvps", "blendvpd", "ptest", "adcx",
                 "phaddw_x", "phaddd_x", "phaddsw_x", "phsubw_x",
                 "phsubd_x", "phsubsw_x", "psignb_x", "psignw_x",
                 "psignd_x", "phminposuw", "aesimc"}
for b, nm in _SSE4_66_0F38:
    if nm in _NO_EVEX_0F38:
        continue
    _s(f"ev_{_vx(nm)}", f"e0F38 p66 {b:02X} /r", _VEXM)
_s("ev_movntdqa", "e0F38 p66 2A /r m", _VEXM)
# Post-AVX2 ISA families the 2017-era reference table predates:
# GFNI, VAES, VPCLMULQDQ, AVX-512 VNNI / VPOPCNTDQ / BITALG / IFMA /
# VBMI and the BF16 plane — both VEX and EVEX spellings where both
# exist (SDM vol. 2 current maps).
_s("gf2p8mulb", "p66 0F 38 CF /r", ALL)
_s("gf2p8affineqb", "p66 0F 3A CE /r ib", ALL)
_s("gf2p8affineinvqb", "p66 0F 3A CF /r ib", ALL)
_s("vgf2p8mulb", "v0F38 p66 CF /r", _VEXM)
_s("vgf2p8affineqb", "v0F3A p66 CE /r ib", _VEXM)
_s("vgf2p8affineinvqb", "v0F3A p66 CF /r ib", _VEXM)
_s("ev_gf2p8mulb", "e0F38 p66 CF /r", _VEXM)
_s("ev_gf2p8affineqb", "e0F3A p66 CE /r ib", _VEXM)
_s("ev_gf2p8affineinvqb", "e0F3A p66 CF /r ib", _VEXM)
for b, nm in [(0x50, "vpdpbusd"), (0x51, "vpdpbusds"),
              (0x52, "vpdpwssd"), (0x53, "vpdpwssds")]:
    _s(nm, f"v0F38 p66 {b:02X} /r", _VEXM)          # AVX-VNNI
    _s(f"ev_{nm[1:]}", f"e0F38 p66 {b:02X} /r", _VEXM)
_s("evpopcntd", "e0F38 p66 55 /r", _VEXM)           # VPOPCNTDQ
_s("evpopcntb", "e0F38 p66 54 /r", _VEXM)           # BITALG
_s("evpshufbitqmb", "e0F38 p66 8F /r", _VEXM)
_s("evpmadd52luq", "e0F38 p66 B4 /r", _VEXM)        # IFMA
_s("evpmadd52huq", "e0F38 p66 B5 /r", _VEXM)
_s("evpermb", "e0F38 p66 8D /r", _VEXM)             # VBMI
_s("evpmultishiftqb", "e0F38 p66 83 /r", _VEXM)
_s("evpermi2b", "e0F38 p66 75 /r", _VEXM)
_s("evpermt2b", "e0F38 p66 7D /r", _VEXM)
_s("evcvtne2ps2bf16", "e0F38 pF2 72 /r", _VEXM)     # BF16
_s("evcvtneps2bf16", "e0F38 pF3 72 /r", _VEXM)
_s("evdpbf16ps", "e0F38 pF3 52 /r", _VEXM)
# (VAES-512 ev_aesenc.. arrive via the promotion loop above)
_s("ev_pclmulqdq", "e0F3A p66 44 /r ib", _VEXM)     # VPCLMULQDQ-512

# AVX-512 gathers/scatters (VSIB, memory-only; scatter is EVEX-native
# with no VEX dual).
for b, nm in [(0x90, "evpgatherdd"), (0x91, "evpgatherqd"),
              (0x92, "evgatherdps"), (0x93, "evgatherqps"),
              (0xA0, "evpscatterdd"), (0xA1, "evpscatterqd"),
              (0xA2, "evscatterdps"), (0xA3, "evscatterqps")]:
    _s(nm, f"e0F38 p66 {b:02X} /r m", _VEXM)
# Truncating down-converts (EVEX-native, pF3 plane): vpmov[s|us]?{q,d,w}
# to narrower elements; W/size handled by the payload rolls.
for b, nm in [(0x10, "evpmovuswb"), (0x11, "evpmovusdb"),
              (0x12, "evpmovusqb"), (0x13, "evpmovusdw"),
              (0x14, "evpmovusqw"), (0x15, "evpmovusqd"),
              (0x20, "evpmovswb"), (0x21, "evpmovsdb"),
              (0x22, "evpmovsqb"), (0x23, "evpmovsdw"),
              (0x24, "evpmovsqw"), (0x25, "evpmovsqd"),
              (0x30, "evpmovwb"), (0x31, "evpmovdb"),
              (0x32, "evpmovqb"), (0x33, "evpmovdw"),
              (0x34, "evpmovqw"), (0x35, "evpmovqd")]:
    _s(nm, f"e0F38 pF3 {b:02X} /r", _VEXM)
# Mask<->vector moves and mask tests (pF3 0F38 plane).
for b, nm in [(0x28, "evpmovm2b"), (0x29, "evpmovb2m"),
              (0x38, "evpmovm2d"), (0x39, "evpmovd2m")]:
    _s(nm, f"e0F38 pF3 {b:02X} /r rr", _VEXM)
_s("evptestm", "e0F38 p66 26 /r", _VEXM)
_s("evptestnm", "e0F38 pF3 26 /r", _VEXM)
_s("evptestmd", "e0F38 p66 27 /r", _VEXM)
_s("evptestnmd", "e0F38 pF3 27 /r", _VEXM)
# Math helper planes: scalef/getexp/rcp14/rsqrt14, fpclass/reduce/
# getmant-sd, and the 32x8/64x2 insert/extract shapes.
_s("evscalefps", "e0F38 p66 2C /r", _VEXM)
_s("evscalefss", "e0F38 p66 2D /r", _VEXM)
_s("evgetexpps", "e0F38 p66 42 /r", _VEXM)
_s("evgetexpss", "e0F38 p66 43 /r", _VEXM)
_s("evrcp14ps", "e0F38 p66 4C /r", _VEXM)
_s("evrcp14ss", "e0F38 p66 4D /r", _VEXM)
_s("evrsqrt14ps", "e0F38 p66 4E /r", _VEXM)
_s("evrsqrt14ss", "e0F38 p66 4F /r", _VEXM)
_s("evfpclassps", "e0F3A p66 66 /r ib", _VEXM)
_s("evfpclassss", "e0F3A p66 67 /r ib", _VEXM)
_s("evreduceps", "e0F3A p66 56 /r ib", _VEXM)
_s("evreducess", "e0F3A p66 57 /r ib", _VEXM)
_s("evinsertf32x4", "e0F3A p66 18 /r ib", _VEXM)
_s("evinsertf64x4", "e0F3A p66 1A /r ib", _VEXM)
_s("evinserti32x4", "e0F3A p66 38 /r ib", _VEXM)
_s("evinserti64x4", "e0F3A p66 3A /r ib", _VEXM)
_s("evextracti32x4", "e0F3A p66 39 /r ib", _VEXM)
_s("evextracti64x4", "e0F3A p66 3B /r ib", _VEXM)
_s("evpbroadcastb_r", "e0F38 p66 7A /r rr", _VEXM)
_s("evpbroadcastw_r", "e0F38 p66 7B /r rr", _VEXM)
_s("evpbroadcastd_r", "e0F38 p66 7C /r rr", _VEXM)
_s("evprolvd", "e0F38 p66 15 /r", _VEXM)
_s("evprorvd", "e0F38 p66 14 /r", _VEXM)
_s("evpsravq", "e0F38 p66 46 /r", _VEXM)
_s("evpsllvw", "e0F38 p66 12 /r", _VEXM)
_s("evpsrlvw", "e0F38 p66 10 /r", _VEXM)
_s("evpsravw", "e0F38 p66 11 /r", _VEXM)

# Opmask (k-register) ops: VEX-encoded, pp selects the width family.
for b, nm in [(0x41, "kand"), (0x42, "kandn"), (0x44, "knot"),
              (0x45, "kor"), (0x46, "kxnor"), (0x47, "kxor"),
              (0x4A, "kadd"), (0x4B, "kunpck")]:
    _s(f"{nm}w", f"v0F {b:02X} /r rr", _VEXM)
    _s(f"{nm}b", f"v0F p66 {b:02X} /r rr", _VEXM)
_s("kmovw", "v0F 90 /r", _VEXM)
_s("kmovb", "v0F p66 90 /r", _VEXM)
_s("kmovw_st", "v0F 91 /r m", _VEXM)
_s("kmovw_r", "v0F 92 /r rr", _VEXM)
_s("kmovw_gr", "v0F 93 /r rr", _VEXM)
_s("kortestw", "v0F 98 /r rr", _VEXM)
_s("kortestb", "v0F p66 98 /r rr", _VEXM)
_s("ktestw", "v0F 99 /r rr", _VEXM)
_s("ktestb", "v0F p66 99 /r rr", _VEXM)
_s("kshiftrw", "v0F3A p66 30 /r rr ib", _VEXM)
_s("kshiftrd", "v0F3A p66 31 /r rr ib", _VEXM)
_s("kshiftlw", "v0F3A p66 32 /r rr ib", _VEXM)
_s("kshiftld", "v0F3A p66 33 /r rr ib", _VEXM)

for b, nm in [(0x03, "evalignd"), (0x08, "evrndscaleps"),
              (0x09, "evrndscalepd"), (0x0A, "evrndscaless"),
              (0x0B, "evrndscalesd"), (0x19, "evextractf32x4"),
              (0x1B, "evextractf64x4"), (0x1E, "evpcmpud"),
              (0x1F, "evpcmpd"), (0x23, "evshuff32x4"),
              (0x25, "evpternlogd"), (0x26, "evgetmantps"),
              (0x27, "evgetmantss"), (0x3E, "evpcmpuw"),
              (0x3F, "evpcmpw"), (0x43, "evshufi32x4"),
              (0x50, "evrangeps"), (0x51, "evrangess"),
              (0x54, "evfixupimmps"), (0x55, "evfixupimmss")]:
    _s(nm, f"e0F3A p66 {b:02X} /r ib", _VEXM)

# ---- system / modern-ISA odds and ends ------------------------------

_s("rdrand", "0F C7 /6 rr", ALL)
_s("rdseed", "0F C7 /7 rr", ALL)
_s("rdpid", "pF3 0F C7 /7 rr", ALL)
_s("clflushopt", "p66 0F AE /7 m", ALL)
_s("clwb", "p66 0F AE /6 m", ALL)
_s("ptwrite", "pF3 0F AE /4", ALL)
_s("invept", "p66 0F 38 80 /r m", ALL, PRIV)
_s("invvpid", "p66 0F 38 81 /r m", ALL, PRIV)
_s("invpcid", "p66 0F 38 82 /r m", ALL, PRIV)
_s("movdiri", "0F 38 F9 /r m", ALL)
_s("movdir64b", "p66 0F 38 F8 /r m", ALL)
_s("enqcmds", "pF3 0F 38 F8 /r m", ALL, PRIV)
_s("enqcmd", "pF2 0F 38 F8 /r m", ALL)
_s("wbnoinvd", "pF3 0F 09", ALL, PRIV)
_s("clac", "0F 01 CA", ALL, PRIV)
_s("stac", "0F 01 CB", ALL, PRIV)
_s("encls", "0F 01 CF", ALL, PRIV)
_s("enclu", "0F 01 D7", ALL)
_s("enclv", "0F 01 C0", ALL, PRIV)
_s("xend", "0F 01 D5", ALL)
_s("xtest", "0F 01 D6", ALL)
_s("serialize", "0F 01 E8", ALL)
_s("rdpkru", "0F 01 EE", ALL)
_s("wrpkru", "0F 01 EF", ALL)
_s("monitorx", "0F 01 FA", ALL, PRIV)
_s("mwaitx", "0F 01 FB", ALL, PRIV)
_s("clzero", "0F 01 FC", ALL)
_s("rdpru", "0F 01 FD", ALL)
# SHA extensions (no-prefix 0F38/0F3A)
_s("sha1nexte", "0F 38 C8 /r", ALL)
_s("sha1msg1", "0F 38 C9 /r", ALL)
_s("sha1msg2", "0F 38 CA /r", ALL)
_s("sha256rnds2", "0F 38 CB /r", ALL)
_s("sha256msg1", "0F 38 CC /r", ALL)
_s("sha256msg2", "0F 38 CD /r", ALL)
_s("sha1rnds4", "0F 3A CC /r ib", ALL)
# SSE4a (AMD)
_s("movntss", "pF3 0F 2B /r m", ALL)
_s("movntsd", "pF2 0F 2B /r m", ALL)
# (SSE4a extrq/insertq omitted: 0F 78/79 collide with vmread/vmwrite
# and differ in imm length only by prefix — the length decoder's
# two-byte map is prefix-blind by design.)
# 3DNow!: 0F 0F modrm + operation-suffix byte (AMD appendix D).  The
# named entries pin the defined suffixes via the sXX token; the
# `now3d` wildcard keeps sweeping the UNDEFINED suffix space — for a
# fuzzer both matter.  All share the (0F,0F) length shape.
for sfx, nm in [(0x0C, "pi2fw"), (0x0D, "pi2fd"), (0x1C, "pf2iw"),
                (0x1D, "pf2id"), (0x8A, "pfnacc"), (0x8E, "pfpnacc"),
                (0x90, "pfcmpge"), (0x94, "pfmin"), (0x96, "pfrcp"),
                (0x97, "pfrsqrt"), (0x9A, "pfsub"), (0x9E, "pfadd"),
                (0xA0, "pfcmpgt"), (0xA4, "pfmax"), (0xA6, "pfrcpit1"),
                (0xA7, "pfrsqit1"), (0xAA, "pfsubr"), (0xAE, "pfacc"),
                (0xB0, "pfcmpeq"), (0xB4, "pfmul"), (0xB6, "pfrcpit2"),
                (0xB7, "pmulhrw"), (0xBB, "pswapd"), (0xBF, "pavgusb")]:
    _s(nm, f"0F 0F /r s{sfx:02X}", ALL)
_s("now3d", "0F 0F /r ib", ALL)
_s("femms", "0F 0E", ALL)

# SSE reg-reg movers that share opcodes with the MEMONLY movlps/movhps
# rows (mod=3 selects the register form per SDM).
_s("movhlps", "0F 12 /r rr", ALL)
_s("movlhps", "0F 16 /r rr", ALL)
_s("pause", "F3 90", ALL, FIXEDENC)

# XSAVE-state family: compacted/supervisor forms + the REX.W-spelled
# 64-bit layouts the reference tables as separate entries.
_s("xsaveopt", "0F AE /6 m", ALL)
_s("xsavec", "0F C7 /4 m", ALL)
_s("xsaves", "0F C7 /5 m", ALL, PRIV)
_s("xrstors", "0F C7 /3 m", ALL, PRIV)
for nm, enc in [("fxsave64", "48 0F AE /0 m"),
                ("fxrstor64", "48 0F AE /1 m"),
                ("xsave64", "48 0F AE /4 m"),
                ("xrstor64", "48 0F AE /5 m"),
                ("xsaveopt64", "48 0F AE /6 m"),
                ("xsavec64", "48 0F C7 /4 m"),
                ("xsaves64", "48 0F C7 /5 m"),
                ("xrstors64", "48 0F C7 /3 m")]:
    _s(nm, enc, X64, PRIV if "xsaves" in nm or "xrstors" in nm else 0)

# TSX: XBEGIN's rel is operand-size wide; XABORT carries a status imm.
_s("xbegin", "C7 F8 cz", ALL)
_s("xabort", "C6 F8 ib", ALL)

# 16-byte compare-exchange: the REX.W form of the 0F C7 /1 group.
_s("cmpxchg16b", "48 0F C7 /1 m", X64)
_s("cmpxchg16b_lock", "F0 48 0F C7 /1 m", X64)

# Canonical multi-byte NOPs (SDM table 4-12).  Length-decode flows
# through the 0F 1F modrm group / prefix rules; these entries give the
# generator the recommended byte sequences.
_s("nop2", "66 90", ALL, FIXEDENC)
_s("nop3", "0F 1F 00", ALL, FIXEDENC)
# the SIB/disp forms assume 32-bit modrm addressing, and the literal
# bytes must not pick up random prefixes (a 67 would change how the
# embedded modrm decodes) — FIXEDENC emits them verbatim
_s("nop4", "0F 1F 40 00", PROT32 | LONG64, FIXEDENC)
_s("nop5", "0F 1F 44 00 00", PROT32 | LONG64, FIXEDENC)
_s("nop6", "66 0F 1F 44 00 00", PROT32 | LONG64, FIXEDENC)
_s("nop7", "0F 1F 80 00 00 00 00", PROT32 | LONG64, FIXEDENC)
_s("nop8", "0F 1F 84 00 00 00 00 00", PROT32 | LONG64, FIXEDENC)

# x87 oddities kept by hardware for compatibility (decode as the
# register families they alias).
_s("ffreep", "DF C0 +r", ALL)
_s("feni8087_nop", "DB E0", ALL)
_s("fdisi8087_nop", "DB E1", ALL)
_s("fsetpm287_nop", "DB E4", ALL)

# ---- VMX VMCS-pointer ops: the memory forms of the 0F C7 group ------
# (rdrand/rdseed above are the register forms of /6 and /7; _pick
# resolves by modrm.mod).
_s("vmptrld", "0F C7 /6 m", ALL, PRIV)
_s("vmclear", "p66 0F C7 /6 m", ALL, PRIV)
_s("vmxon", "pF3 0F C7 /6 m", ALL, PRIV)
_s("vmptrst", "0F C7 /7 m", ALL, PRIV)

# ---- MPX bounds registers (0F 1A/1B prefix planes) ------------------
_s("bndldx", "0F 1A /r m", PROT32 | LONG64)
_s("bndstx", "0F 1B /r m", PROT32 | LONG64)
_s("bndmov", "p66 0F 1A /r", PROT32 | LONG64)
_s("bndmov_st", "p66 0F 1B /r", PROT32 | LONG64)
_s("bndcl", "pF3 0F 1A /r", PROT32 | LONG64)
_s("bndmk", "pF3 0F 1B /r m", PROT32 | LONG64)
_s("bndcu", "pF2 0F 1A /r", PROT32 | LONG64)
_s("bndcn", "pF2 0F 1B /r", PROT32 | LONG64)

# ---- string/convert width spellings ---------------------------------
# The base entries (movsd "A5", cbw "98", ...) already sweep widths
# via the random 66/REX.W rolls; these named forms pin the width the
# way the reference's per-width entries do (insw/movsq/cdqe/...).
for op, nm in [(0x6D, "insw"), (0x6F, "outsw"), (0xA5, "movsw"),
               (0xA7, "cmpsw"), (0xAB, "stosw"), (0xAD, "lodsw"),
               (0xAF, "scasw")]:
    _s(nm, f"p66 {op:02X}", ALL, PRIV if op in (0x6D, 0x6F) else 0)
for op, nm in [(0xA5, "movsq"), (0xA7, "cmpsq"), (0xAB, "stosq"),
               (0xAD, "lodsq"), (0xAF, "scasq")]:
    _s(nm, f"48 {op:02X}", X64)
_s("cdqe", "48 98", X64)
_s("cqo", "48 99", X64)

# ---- LOCK-prefixed atomics ------------------------------------------
# The reference's table carries *_LOCK entries for every lockable
# memory form; same here, generated from the lockable spec list (the
# F0 byte rides in the opcode so it is always adjacent, and MEMONLY
# keeps modrm off the register forms, where LOCK is #UD).
for i, op in enumerate(_ARITH):
    base = i * 8
    if op == "cmp":
        continue  # cmp has no LOCK form
    _s(f"{op}_lock", f"F0 {base:02X} /r m", ALL)
    _s(f"{op}_lock", f"F0 {base + 1:02X} /r m", ALL)
for d, op in enumerate(_ARITH):
    if op == "cmp":
        continue
    _s(f"{op}_lock", f"F0 80 /{d} ib m", ALL)
    _s(f"{op}_lock", f"F0 81 /{d} iz m", ALL)
    _s(f"{op}_lock", f"F0 83 /{d} ib m", ALL)
_s("inc_lock", "F0 FE /0 m", ALL)
_s("dec_lock", "F0 FE /1 m", ALL)
_s("inc_lock", "F0 FF /0 m", ALL)
_s("dec_lock", "F0 FF /1 m", ALL)
_s("not_lock", "F0 F6 /2 m", ALL)
_s("neg_lock", "F0 F6 /3 m", ALL)
_s("not_lock", "F0 F7 /2 m", ALL)
_s("neg_lock", "F0 F7 /3 m", ALL)
_s("xchg_lock", "F0 86 /r m", ALL)
_s("xchg_lock", "F0 87 /r m", ALL)
_s("xadd_lock", "F0 0F C0 /r m", ALL)
_s("xadd_lock", "F0 0F C1 /r m", ALL)
_s("bts_lock", "F0 0F AB /r m", ALL)
_s("btr_lock", "F0 0F B3 /r m", ALL)
_s("btc_lock", "F0 0F BB /r m", ALL)
_s("bts_lock", "F0 0F BA /5 ib m", ALL)
_s("btr_lock", "F0 0F BA /6 ib m", ALL)
_s("btc_lock", "F0 0F BA /7 ib m", ALL)
_s("cmpxchg_lock", "F0 0F B0 /r m", ALL)
_s("cmpxchg_lock", "F0 0F B1 /r m", ALL)
_s("cmpxchg8b_lock", "F0 0F C7 /1 m", ALL)

# ---- AMD FMA4 / VPERMIL2 (VEX 0F3A with the is4 register byte) ------
_FMA4 = [(0x5C, "vfmaddsubps"), (0x5D, "vfmaddsubpd"),
         (0x5E, "vfmsubaddps"), (0x5F, "vfmsubaddpd"),
         (0x68, "vfmaddps"), (0x69, "vfmaddpd"), (0x6A, "vfmaddss"),
         (0x6B, "vfmaddsd"), (0x6C, "vfmsubps"), (0x6D, "vfmsubpd"),
         (0x6E, "vfmsubss"), (0x6F, "vfmsubsd"), (0x78, "vfnmaddps"),
         (0x79, "vfnmaddpd"), (0x7A, "vfnmaddss"), (0x7B, "vfnmaddsd"),
         (0x7C, "vfnmsubps"), (0x7D, "vfnmsubpd"), (0x7E, "vfnmsubss"),
         (0x7F, "vfnmsubsd")]
for b, nm in _FMA4:
    _s(nm, f"v0F3A p66 {b:02X} /r ib", _VEXM)  # ib = is4 operand
_s("vpermil2ps", "v0F3A p66 48 /r ib", _VEXM)
_s("vpermil2pd", "v0F3A p66 49 /r ib", _VEXM)

# ---- AMD XOP map 8: MACs, permutes, rotates-by-imm, compares --------
_XOP8 = [(0x85, "vpmacssww"), (0x86, "vpmacsswd"), (0x87, "vpmacssdql"),
         (0x8E, "vpmacssdd"), (0x8F, "vpmacssdqh"), (0x95, "vpmacsww"),
         (0x96, "vpmacswd"), (0x97, "vpmacsdql"), (0x9E, "vpmacsdd"),
         (0x9F, "vpmacsdqh"), (0xA2, "vpcmov"), (0xA3, "vpperm"),
         (0xA6, "vpmadcsswd"), (0xB6, "vpmadcswd"),
         (0xC0, "vprotb_i"), (0xC1, "vprotw_i"), (0xC2, "vprotd_i"),
         (0xC3, "vprotq_i"), (0xCC, "vpcomb"), (0xCD, "vpcomw"),
         (0xCE, "vpcomd"), (0xCF, "vpcomq"), (0xEC, "vpcomub"),
         (0xED, "vpcomuw"), (0xEE, "vpcomud"), (0xEF, "vpcomuq")]
for b, nm in _XOP8:
    _s(nm, f"x08 {b:02X} /r ib", _VEXM)

# ---- AMD XOP map 9: TBM groups, LWP control, frcz, shifts/rotates ---
for d, nm in [(1, "blcfill"), (2, "blsfill"), (3, "blcs"), (4, "tzmsk"),
              (5, "blcic"), (6, "blsic"), (7, "t1mskc")]:
    _s(nm, f"x09 01 /{d}", _VEXM)
_s("blcmsk", "x09 02 /1", _VEXM)
_s("blci", "x09 02 /6", _VEXM)
_s("llwpcb", "x09 12 /0 rr", _VEXM)
_s("slwpcb", "x09 12 /1 rr", _VEXM)
_XOP9 = [(0x80, "vfrczps"), (0x81, "vfrczpd"), (0x82, "vfrczss"),
         (0x83, "vfrczsd"), (0x90, "vprotb"), (0x91, "vprotw"),
         (0x92, "vprotd"), (0x93, "vprotq"), (0x94, "vpshlb"),
         (0x95, "vpshlw"), (0x96, "vpshld"), (0x97, "vpshlq"),
         (0x98, "vpshab"), (0x99, "vpshaw"), (0x9A, "vpshad"),
         (0x9B, "vpshaq"), (0xC1, "vphaddbw"), (0xC2, "vphaddbd"),
         (0xC3, "vphaddbq"), (0xC6, "vphaddwd"), (0xC7, "vphaddwq"),
         (0xCB, "vphadddq"), (0xD1, "vphaddubw"), (0xD2, "vphaddubd"),
         (0xD3, "vphaddubq"), (0xD6, "vphadduwd"), (0xD7, "vphadduwq"),
         (0xDB, "vphaddudq"), (0xE1, "vphsubbw"), (0xE2, "vphsubwd"),
         (0xE3, "vphsubdq")]
for b, nm in _XOP9:
    _s(nm, f"x09 {b:02X} /r", _VEXM)

# ---- AMD XOP map A: bextr-imm32 + LWP inserts -----------------------
_s("bextr_xop", "x0A 10 /r id", _VEXM)
_s("lwpins", "x0A 12 /0 id", _VEXM)
_s("lwpval", "x0A 12 /1 id", _VEXM)

INSNS: list[Insn] = [_parse_spec(*e) for e in _SPEC]

# -- lookup maps for decode -------------------------------------------


def _build_maps():
    one: dict[int, object] = {}     # byte -> Insn | {digit: Insn} | list
    two: dict[int, object] = {}     # 0F xx
    m38: dict[int, Insn] = {}
    m3a: dict[int, Insn] = {}
    fixed: dict[bytes, Insn] = {}   # full fixed encodings (0F 01 C1 ..)
    fixed1: dict[bytes, Insn] = {}  # legacy 2-byte fixed (C7 F8 ..)
    vex: dict[tuple, Insn] = {}     # (map, opcode) -> Insn
    evex: dict[tuple, Insn] = {}    # (map, opcode) -> Insn (AVX-512)

    def add(table, key, insn):
        if insn.reg >= 0:
            grp = table.setdefault(key, {})
            assert isinstance(grp, dict), (hex(key), insn.name)
            grp.setdefault(insn.reg, []).append(insn)
        else:
            lst = table.setdefault(key, [])
            assert isinstance(lst, list), (hex(key), insn.name)
            lst.append(insn)

    for insn in INSNS:
        if insn.flags & FIXEDENC:
            # generation-only verbatim rows (canonical NOPs, pause):
            # decode resolves their bytes through the prefix rules and
            # the group entries, so they must not pollute the maps.
            continue
        if insn.flags & VEX:
            vex.setdefault((insn.vexmap, insn.opcode[-1]), insn)
            continue
        if insn.flags & EVEX:
            evex.setdefault((insn.vexmap, insn.opcode[-1]), insn)
            continue
        op = insn.opcode
        # Literal F0 (LOCK) / 48 (REX.W) lead bytes are generation-
        # side spellings; the decoder consumes them as prefixes, so
        # the map key is the opcode behind them (the base entry at
        # that key already provides the same length shape).
        while len(op) > 1 and op[0] in (0xF0, 0x48):
            op = op[1:]
        if insn.plusr:
            for r in range(8):
                b = bytes(op[:-1]) + bytes([op[-1] + r])
                if len(b) == 1:
                    add(one, b[0], insn)
                elif b[0] == 0x0F:
                    add(two, b[1], insn)
                else:
                    # x87 register family (D9 C0+r fld st(i), ...):
                    # length-equivalent to the escape byte's modrm
                    # group; recorded so the generator can emit the
                    # specific form.  decode() resolves these through
                    # the group entry at the escape byte.
                    continue
            continue
        if len(op) == 2 and 0xD8 <= op[0] <= 0xDF:
            # fixed x87 register encoding (DB E3 fninit, DF E0
            # fnstsw-ax, ...): same story — generation-only spec.
            continue
        if len(op) >= 3 and op[0] == 0x0F and op[1] == 0x38:
            m38.setdefault(op[2], insn)
        elif len(op) >= 3 and op[0] == 0x0F and op[1] == 0x3A:
            m3a.setdefault(op[2], insn)
        elif len(op) == 3 and op[0] == 0x0F:
            fixed[op] = insn          # 0F 01 C1 style
        elif len(op) == 2 and op[0] == 0x0F:
            add(two, op[1], insn)
        elif len(op) == 2 and not insn.modrm:
            # legacy fixed 2-byte: trailing opcode-extension byte
            # (C7 F8 xbegin / C6 F8 xabort); F3-led spellings (pause)
            # decode through the prefix path, entry kept for
            # generation only.
            fixed1[op] = insn
        else:
            add(one, op[0], insn)
    return one, two, m38, m3a, fixed, fixed1, vex, evex


(_MAP1, _MAP2, _MAP38, _MAP3A, _FIXED, _FIXED1, _VEXMAP,
 _EVEXMAP) = _build_maps()

LEGACY_PREFIXES = frozenset(
    [0x66, 0x67, 0xF0, 0xF2, 0xF3, 0x2E, 0x36, 0x3E, 0x26, 0x64, 0x65])


def _pick(table_entry, regbits, mode, mod=-1):
    """Resolve a one/two-byte map entry to an Insn valid in `mode`.

    mod: the modrm mod bits at the decode position (-1 if unknown) —
    entries whose MEMONLY/REGONLY contradicts it are deprioritized so
    a memory-only prefix variant cannot shadow a register-form one
    sharing the opcode byte."""
    if table_entry is None:
        return None
    cands = (table_entry.get(regbits) or [])         if isinstance(table_entry, dict) else table_entry
    fallback = None
    for c in cands:
        if not (c.modes & mode):
            continue
        if mod >= 0 and ((c.flags & MEMONLY and mod == 3) or
                         (c.flags & REGONLY and mod != 3)):
            if fallback is None:
                fallback = c
            continue
        return c
    return fallback


def _opsize(mode, osz66, rexw):
    if mode == LONG64:
        return 8 if rexw else (2 if osz66 else 4)
    if mode == PROT32:
        return 2 if osz66 else 4
    return 4 if osz66 else 2


def _addrsize(mode, asz67):
    if mode == LONG64:
        return 4 if asz67 else 8
    if mode == PROT32:
        return 2 if asz67 else 4
    return 4 if asz67 else 2


def _imm_len(tok, osz, asz):
    if tok == "ib" or tok == "cb":
        return 1
    if tok == "iw":
        return 2
    if tok == "id":
        return 4
    if tok in ("iz", "cz"):
        return 2 if osz == 2 else 4
    if tok == "iv":
        return osz
    if tok == "mo":
        return asz
    raise AssertionError(tok)


def _modrm_len(data, pos, asz):
    """Length of modrm+sib+disp starting at pos; -1 if truncated."""
    if pos >= len(data):
        return -1
    modrm = data[pos]
    mod, rm = modrm >> 6, modrm & 7
    n = 1
    if mod == 3:
        return n
    if asz == 2:  # 16-bit addressing: no SIB, disp8/16
        if mod == 1:
            n += 1
        elif mod == 2 or (mod == 0 and rm == 6):
            n += 2
        return n
    if rm == 4:  # SIB
        if pos + 1 >= len(data):
            return -1
        sib = data[pos + 1]
        n += 1
        if mod == 0 and (sib & 7) == 5:
            n += 4
    if mod == 1:
        n += 1
    elif mod == 2 or (mod == 0 and rm == 5):
        n += 4
    return n


def decode(mode: int, data: bytes) -> int:
    """Length of the instruction at data[0:] in `mode`, or -1."""
    pos, osz66, asz67 = 0, False, False
    rexw = False
    # legacy prefixes
    while pos < len(data) and data[pos] in LEGACY_PREFIXES:
        if data[pos] == 0x66:
            osz66 = True
        elif data[pos] == 0x67:
            asz67 = True
        pos += 1
        if pos > 14:
            return -1
    if pos >= len(data):
        return -1
    # REX
    if mode == LONG64 and 0x40 <= data[pos] <= 0x4F:
        rexw = bool(data[pos] & 8)
        pos += 1
        if pos >= len(data):
            return -1
    osz = _opsize(mode, osz66, rexw)
    asz = _addrsize(mode, asz67)
    b0 = data[pos]
    # EVEX: 62 is EVEX in long mode always; in prot32 only when the
    # payload's top two bits are 11 (else BOUND).  Payload is always
    # 3 bytes; disp8 compression rescales the displacement value, not
    # its size, so the tail length rules are the VEX ones.
    if b0 == 0x62 and pos + 3 < len(data) and (
            mode == LONG64 or
            (mode == PROT32 and (data[pos + 1] & 0xC0) == 0xC0)):
        emap = data[pos + 1] & 0x07
        insn = _EVEXMAP.get((emap, data[pos + 4])) \
            if pos + 4 < len(data) else None
        if insn is None or not (insn.modes & mode):
            return -1
        pos += 5
        # prefix-blind like the VEX path: the (map, opcode) entry may
        # be a different pp-plane's insn, so MEMONLY/REGONLY flags are
        # not enforced here — only length structure is shared.
        n = _modrm_len(data, pos, asz) if insn.modrm else 0
        if n < 0:
            return -1
        pos += n
        for tok in insn.imms:
            pos += _imm_len(tok, osz, asz)
        return pos if pos <= len(data) else -1
    # XOP: 8F with map_select >= 8 (bits 0-4 of the next byte).  A
    # pop_rm modrm has reg == 0, so its byte & 0x1F is always <= 7 —
    # the two encodings cannot collide.
    if b0 == 0x8F and pos + 3 < len(data) \
            and (data[pos + 1] & 0x1F) >= 8:
        vmap = data[pos + 1] & 0x1F
        opb = data[pos + 3]
        insn = _VEXMAP.get((vmap, opb))
        if insn is None or not (insn.modes & mode):
            return -1
        pos += 4
        n = _modrm_len(data, pos, asz) if insn.modrm else 0
        if n < 0:
            return -1
        pos += n
        for tok in insn.imms:
            pos += _imm_len(tok, osz, asz)
        return pos if pos <= len(data) else -1
    # VEX: C4/C5 are VEX in long mode always; in prot32 only when the
    # next byte's top two bits are 11 (else LES/LDS).
    if b0 in (0xC4, 0xC5) and pos + 1 < len(data) and (
            mode == LONG64 or
            (mode == PROT32 and (data[pos + 1] & 0xC0) == 0xC0)):
        if b0 == 0xC5:
            vmap, vlen = 1, 2
            if pos + 2 >= len(data):
                return -1
            opb = data[pos + 2]
        else:
            if pos + 3 >= len(data):
                return -1
            vmap = data[pos + 1] & 0x1F
            rexw = bool(data[pos + 2] & 0x80)
            vlen = 3
            opb = data[pos + 3]
        insn = _VEXMAP.get((vmap, opb))
        if insn is None or not (insn.modes & mode):
            return -1
        pos += vlen + 1
        n = _modrm_len(data, pos, asz) if insn.modrm else 0
        if n < 0:
            return -1
        pos += n
        osz = _opsize(mode, osz66, rexw)
        for tok in insn.imms:
            pos += _imm_len(tok, osz, asz)
        return pos if pos <= len(data) else -1
    if b0 == 0x0F:
        if pos + 1 >= len(data):
            return -1
        b1 = data[pos + 1]
        if b1 in (0x38, 0x3A):
            if pos + 2 >= len(data):
                return -1
            insn = (_MAP38 if b1 == 0x38 else _MAP3A).get(data[pos + 2])
            if insn is None or not (insn.modes & mode):
                return -1
            pos += 3
        else:
            # fixed 3-byte first (0F 01 C1 ...)
            if pos + 2 < len(data):
                insn = _FIXED.get(bytes([0x0F, b1, data[pos + 2]]))
                if insn is not None and insn.modes & mode:
                    pos += 3
                    for tok in insn.imms:
                        pos += _imm_len(tok, osz, asz)
                    return pos if pos <= len(data) else -1
            regbits = (data[pos + 2] >> 3) & 7 if pos + 2 < len(data) else 0
            mod = (data[pos + 2] >> 6) if pos + 2 < len(data) else -1
            insn = _pick(_MAP2.get(b1), regbits, mode, mod)
            if insn is None:
                return -1
            pos += 2
    else:
        # fixed legacy 2-byte first (C7 F8 xbegin, C6 F8 xabort): the
        # trailing byte is an opcode extension, not modrm — consume
        # both and fall through to the shared D64/imm epilogue.
        fixed1 = _FIXED1.get(bytes([b0, data[pos + 1]])) \
            if pos + 1 < len(data) else None
        if fixed1 is not None and fixed1.modes & mode:
            insn = fixed1
            pos += 2
        else:
            regbits = (data[pos + 1] >> 3) & 7 \
                if pos + 1 < len(data) else 0
            mod = (data[pos + 1] >> 6) if pos + 1 < len(data) else -1
            insn = _pick(_MAP1.get(b0), regbits, mode, mod)
            if insn is None:
                return -1
            pos += 1
    if insn.flags & D64 and mode == LONG64 and not osz66:
        osz = 8
    if insn.modrm:
        n = _modrm_len(data, pos, asz)
        if n < 0:
            return -1
        mod = data[pos] >> 6
        if insn.flags & MEMONLY and mod == 3:
            return -1
        if insn.flags & REGONLY and mod != 3:
            return -1
        pos += n
    for tok in insn.imms:
        pos += _imm_len(tok, osz, asz)
    return pos if pos <= len(data) else -1


# -- generation --------------------------------------------------------

@dataclass
class Config:
    mode: int = LONG64
    priv: bool = True       # allow privileged instructions
    avx: bool = True        # allow VEX-encoded instructions
    len_insns: int = 10     # instructions per text blob


_MODE_CACHE: dict[tuple, list] = {}


def mode_insns(cfg: Config) -> list[Insn]:
    key = (cfg.mode, cfg.priv, cfg.avx)
    got = _MODE_CACHE.get(key)
    if got is None:
        got = [i for i in INSNS
               if i.modes & cfg.mode
               and (cfg.priv or not i.priv)
               and (cfg.avx or not i.flags & (VEX | EVEX))]
        _MODE_CACHE[key] = got
    return got


def _gen_modrm(insn: Insn, asz: int, r: random.Random) -> bytes:
    out = bytearray()
    regbits = insn.reg if insn.reg >= 0 else r.randrange(8)
    if insn.flags & REGONLY:
        mod = 3
    elif insn.flags & MEMONLY:
        mod = r.randrange(3)
    else:
        mod = r.randrange(4)
    rm = r.randrange(8)
    out.append((mod << 6) | (regbits << 3) | rm)
    if mod == 3:
        return bytes(out)
    if asz == 2:
        if mod == 1:
            out.append(r.randrange(256))
        elif mod == 2 or (mod == 0 and rm == 6):
            out += r.randrange(1 << 16).to_bytes(2, "little")
        return bytes(out)
    if rm == 4:
        sib = r.randrange(256)
        out.append(sib)
        if mod == 0 and (sib & 7) == 5:
            out += r.randrange(1 << 32).to_bytes(4, "little")
    if mod == 1:
        out.append(r.randrange(256))
    elif mod == 2 or (mod == 0 and rm == 5):
        out += r.randrange(1 << 32).to_bytes(4, "little")
    return bytes(out)


_INTERESTING_IMM = [0, 1, 0x7F, 0x80, 0xFF, 0x100, 0x7FFF, 0x8000,
                    0xFFFF, 0x7FFFFFFF, 0x80000000, 0xFFFFFFFF]


def _gen_imm(nbytes: int, r: random.Random) -> bytes:
    if r.randrange(4) == 0:
        v = _INTERESTING_IMM[r.randrange(len(_INTERESTING_IMM))]
    else:
        v = r.randrange(1 << (8 * nbytes))
    return (v & ((1 << (8 * nbytes)) - 1)).to_bytes(nbytes, "little")


def generate_insn(cfg: Config, r: random.Random) -> bytes:
    """One structurally-valid instruction for cfg.mode."""
    insns = mode_insns(cfg)
    insn = insns[r.randrange(len(insns))]
    out = bytearray()
    osz66 = asz67 = rexw = False
    if insn.flags & FIXEDENC:
        return bytes(insn.opcode)  # complete encoding, verbatim
    if insn.flags & EVEX:
        # 62 P0 P1 P2 opcode [modrm...] — P0: RXBR'0mmm (all extension
        # bits 1 = "not extended"), P1: Wvvvv1pp, P2: zL'Lb V'aaa.
        opb = insn.opcode[-1]
        p0 = 0xF0 | insn.vexmap
        p1 = 0x7C | _PP[insn.mprefix]   # W=0, vvvv=1111, bit2=1
        p2 = 0x08 | (r.randrange(3) << 5) | r.randrange(8)  # V'=1, L, aaa
        out += bytes([0x62, p0, p1, p2, opb])
        if insn.modrm:
            out += _gen_modrm(insn, _addrsize(cfg.mode, asz67), r)
        for tok in insn.imms:
            out += _gen_imm(_imm_len(tok, _opsize(cfg.mode, False, False),
                                     _addrsize(cfg.mode, asz67)), r)
        return bytes(out)
    if insn.flags & VEX:
        # optional 67 prefix only (66/F2/F3 change VEX pp semantics)
        if r.randrange(8) == 0:
            out.append(0x67)
            asz67 = True
        opb = insn.opcode[-1]
        pp = _PP[insn.mprefix]  # mandatory prefix rides the pp field
        if insn.vexmap >= 8:
            # XOP: 8F escape, 3-byte payload only (no 2-byte form).
            b1 = 0xE0 | insn.vexmap
            b2 = (r.randrange(256) & 0x7C) | pp
            out += bytes([0x8F, b1, b2, opb])
            if insn.modrm:
                out += _gen_modrm(insn, _addrsize(cfg.mode, asz67), r)
            for tok in insn.imms:
                out += _gen_imm(
                    _imm_len(tok, _opsize(cfg.mode, False, False),
                             _addrsize(cfg.mode, asz67)), r)
            return bytes(out)
        if insn.vexmap == 1 and r.randrange(2) == 0:
            # C5 R'vvvvLpp: top two bits must be 11 outside long mode
            # (the prot32 VEX-vs-LDS disambiguation).
            b1 = (r.randrange(256) & 0x7C) | pp
            if cfg.mode != LONG64:
                b1 |= 0xC0
            else:
                b1 |= 0x80 if r.randrange(2) else 0xC0
            out += bytes([0xC5, b1])
        else:
            b1 = 0xE0 | insn.vexmap      # R'X'B' = 111, m-mmmm = map
            b2 = (r.randrange(256) & 0x7C) | pp  # W=0
            out += bytes([0xC4, b1, b2])
        out.append(opb)
        if insn.modrm:
            out += _gen_modrm(insn, _addrsize(cfg.mode, asz67), r)
        for tok in insn.imms:
            out += _gen_imm(_imm_len(tok, _opsize(cfg.mode, False, False),
                                     _addrsize(cfg.mode, asz67)), r)
        return bytes(out)
    # legacy prefixes.  A mandatory prefix (SSE/SSE2+ forms) must be
    # present and must be the LAST legacy prefix so it stays adjacent
    # to the opcode; the random 66 roll is suppressed for those insns
    # (66+F3 stacking flips meaning per SDM).
    if insn.mprefix != 0x66 and r.randrange(6) == 0:
        out.append(0x66)
        osz66 = True
    if r.randrange(10) == 0:
        out.append(0x67)
        asz67 = True
    if r.randrange(10) == 0:
        out.append(r.choice([0x2E, 0x36, 0x3E, 0x26, 0x64, 0x65]))
    if insn.mprefix:
        out.append(insn.mprefix)
        if insn.mprefix == 0x66:
            osz66 = True
    opcode = bytearray(insn.opcode)
    if opcode[0] == 0xF0:
        # literal LOCK rides with the legacy prefixes, before REX
        out.append(0xF0)
        del opcode[0]
    rex_literal = len(opcode) > 1 and opcode[0] == 0x48 \
        and cfg.mode == LONG64
    if rex_literal:
        rexw = True  # the spelled REX.W (movsq/cdqe/...) IS the REX
    elif cfg.mode == LONG64 and opcode[0] not in LEGACY_PREFIXES \
            and r.randrange(4) == 0:
        # (suppressed when the opcode spells its own lead prefix —
        # 66 0F 1F nop6, F3 90 pause — REX must touch the opcode)
        rex = 0x40 | r.randrange(16)
        rexw = bool(rex & 8)
        out.append(rex)
    if insn.plusr:
        opcode[-1] += r.randrange(8)
    out += opcode
    osz = _opsize(cfg.mode, osz66, rexw)
    if insn.flags & D64 and cfg.mode == LONG64 and not osz66:
        osz = 8
    asz = _addrsize(cfg.mode, asz67)
    if insn.modrm:
        out += _gen_modrm(insn, asz, r)
    for tok in insn.imms:
        if tok == "ib" and insn.suffix >= 0:
            out.append(insn.suffix)  # fixed 3DNow! operation suffix
        else:
            out += _gen_imm(_imm_len(tok, osz, asz), r)
    return bytes(out)


def generate(cfg: Config, r: random.Random) -> bytes:
    out = bytearray()
    for _ in range(cfg.len_insns):
        if r.randrange(20) == 0:
            out += pseudo(cfg.mode, r)
        else:
            out += generate_insn(cfg, r)
    return bytes(out)


def split_insns(mode: int, data: bytes) -> list[bytes]:
    """Split a blob at instruction boundaries; undecodable tails become
    a single raw chunk (mirrors pkg/ifuzz mutation working at insn
    granularity)."""
    chunks, pos = [], 0
    while pos < len(data):
        n = decode(mode, data[pos:])
        if n <= 0:
            chunks.append(data[pos:])
            break
        chunks.append(data[pos:pos + n])
        pos += n
    return chunks


def mutate(cfg: Config, r: random.Random, data: bytes) -> bytes:
    chunks = split_insns(cfg.mode, data)
    for _ in range(r.randrange(3) + 1):
        op = r.randrange(4)
        if op == 0 or not chunks:  # insert a fresh instruction
            chunks.insert(r.randrange(len(chunks) + 1),
                          generate_insn(cfg, r))
        elif op == 1:              # replace one instruction
            chunks[r.randrange(len(chunks))] = generate_insn(cfg, r)
        elif op == 2 and len(chunks) > 1:  # delete
            del chunks[r.randrange(len(chunks))]
        else:                      # byte-level perturb inside one insn
            i = r.randrange(len(chunks))
            b = bytearray(chunks[i])
            if b:
                b[r.randrange(len(b))] = r.randrange(256)
            chunks[i] = bytes(b)
    return b"".join(chunks)


# -- pseudo sequences (pkg/ifuzz/pseudo.go analogue) -------------------

_MSRS = [0xC0000080, 0xC0000081, 0xC0000082, 0xC0000084, 0xC0000100,
         0xC0000101, 0x1B, 0x3A, 0x8B, 0x174, 0x175, 0x176, 0x277]
_INT_VECS = [0, 1, 3, 4, 6, 8, 13, 14, 0x20, 0x80]


def _mov_r32_imm(mode: int, reg: int, val: int) -> bytes:
    """mov r32, imm32 in any mode (66-prefixed in 16-bit modes)."""
    enc = bytes([0xB8 + reg]) + (val & 0xFFFFFFFF).to_bytes(4, "little")
    if mode in (REAL16, PROT16):
        return b"\x66" + enc
    return enc


def _wrmsr(mode, msr, lo, hi) -> bytes:
    return (_mov_r32_imm(mode, 1, msr) + _mov_r32_imm(mode, 0, lo) +
            _mov_r32_imm(mode, 2, hi) + b"\x0f\x30")


def pseudo(mode: int, r: random.Random) -> bytes:
    """A short system-state-poking sequence."""
    which = r.randrange(8)
    if which == 0:    # write an interesting MSR
        return _wrmsr(mode, _MSRS[r.randrange(len(_MSRS))],
                      r.randrange(1 << 32), r.randrange(1 << 32))
    if which == 1:    # read an MSR
        return _mov_r32_imm(mode, 1,
                            _MSRS[r.randrange(len(_MSRS))]) + b"\x0f\x32"
    if which == 2:    # poke CR0/CR3/CR4 (mov eax, imm; mov crN, eax)
        crn = r.choice([0, 3, 4])
        return (_mov_r32_imm(mode, 0, r.randrange(1 << 32)) +
                bytes([0x0F, 0x22, 0xC0 | (crn << 3)]))
    if which == 3:    # enable PAE paging: cr4.PAE, cr3, EFER.LME, cr0.PG
        return (_mov_r32_imm(mode, 0, 1 << 5) +
                bytes([0x0F, 0x22, 0xE0]) +       # mov cr4, eax
                _mov_r32_imm(mode, 0, r.randrange(1 << 32) & ~0xFFF) +
                bytes([0x0F, 0x22, 0xD8]) +       # mov cr3, eax
                _wrmsr(mode, 0xC0000080, 0x100, 0) +
                _mov_r32_imm(mode, 0, 0x80000001) +
                bytes([0x0F, 0x22, 0xC0]))        # mov cr0, eax
    if which == 4:    # lgdt/lidt from a scratch address
        op = r.choice([0x10, 0x18])  # /2 lgdt, /3 lidt (mod=0 rm=disp)
        if mode in (REAL16, PROT16):
            return bytes([0x0F, 0x01, op | 6]) + \
                r.randrange(1 << 16).to_bytes(2, "little")
        return bytes([0x0F, 0x01, op | 5]) + \
            r.randrange(1 << 32).to_bytes(4, "little")
    if which == 5:    # software interrupt
        return bytes([0xCD, _INT_VECS[r.randrange(len(_INT_VECS))]])
    if which == 6:    # IO port poke: mov dx, port; out dx, al / in al, dx
        port = r.choice([0x20, 0x21, 0x40, 0x43, 0x60, 0x64, 0x70,
                         0x71, 0x3F8, 0xCF8, 0xCFC])
        return (b"\x66" + bytes([0xBA]) +
                (port & 0xFFFFFFFF).to_bytes(4, "little") +
                (b"\xee" if r.randrange(2) else b"\xec")) \
            if mode in (REAL16, PROT16) else \
            (bytes([0xBA]) + port.to_bytes(4, "little") +
             (b"\xee" if r.randrange(2) else b"\xec"))
    # VMX/SVM bringup pokes
    return r.choice([
        bytes([0x0F, 0x01, 0xC1]),  # vmcall
        bytes([0x0F, 0x01, 0xC4]),  # vmxoff
        bytes([0x0F, 0x01, 0xD8]),  # vmrun
        bytes([0x0F, 0x01, 0xD9]),  # vmmcall
        bytes([0x0F, 0x01, 0xDC]),  # stgi
        _mov_r32_imm(mode, 0, r.randrange(1 << 32)) +
        bytes([0x0F, 0x78, 0xC1]),  # vmread
    ])
