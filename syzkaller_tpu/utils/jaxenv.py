"""Pin the jax backend by request.

The tunneled-accelerator plugin ignores the JAX_PLATFORMS env var;
only `jax.config.update("jax_platforms", ...)` is honored, and only
BEFORE any backend initializes — afterwards the update is a silent
no-op.  This helper is the one place implementing that dance
(previously copied across bench.py / conftest / __graft_entry__ /
fuzzer.main): it applies the pin and loudly warns when the pin could
not take effect.
"""

from __future__ import annotations

import os

ENV_VAR = "TZ_JAX_PLATFORM"

#: Default on-disk XLA compilation cache.  The tunneled accelerator
#: compiles the pipeline step in ~2 minutes (link-bound); a persistent
#: cache makes every process after the first compile in seconds, which
#: is the difference between a bench warmup absorbing compile or the
#: timed window starting cold (the r5 139-mutants/s artifact was
#: exactly that).
CACHE_ENV = "JAX_COMPILATION_CACHE_DIR"


def enable_compilation_cache(path: str = "") -> str:
    """Point jax at a persistent compilation cache directory.  Must
    run before the first jax computation; safe to call repeatedly."""
    path = path or os.environ.get(CACHE_ENV, "") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), ".jax_cache")
    try:
        os.makedirs(path, exist_ok=True)
        os.environ.setdefault(CACHE_ENV, path)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
    except Exception:
        return ""  # the cache is an optimization; never fail the caller
    return path


def pin_jax_platform(platform: str = "") -> str:
    """Pin jax to `platform` (or $TZ_JAX_PLATFORM when empty).
    Returns the platform requested ("" = no pin).  Must run before
    the first jax computation in the process."""
    platform = platform or os.environ.get(ENV_VAR, "")
    if not platform:
        return ""
    import jax

    jax.config.update("jax_platforms", platform)
    backend = jax.default_backend()
    if backend != platform:
        from syzkaller_tpu.utils import log

        log.logf(0, "WARNING: jax backend is %r despite %s=%r — the "
                    "pin ran after a backend initialized and was "
                    "silently ignored", backend, ENV_VAR, platform)
    return platform
