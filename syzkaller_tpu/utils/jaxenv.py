"""Pin the jax backend by request.

The tunneled-accelerator plugin ignores the JAX_PLATFORMS env var;
only `jax.config.update("jax_platforms", ...)` is honored, and only
BEFORE any backend initializes — afterwards the update is a silent
no-op.  This helper is the one place implementing that dance
(previously copied across bench.py / conftest / __graft_entry__ /
fuzzer.main): it applies the pin and loudly warns when the pin could
not take effect.
"""

from __future__ import annotations

import os

ENV_VAR = "TZ_JAX_PLATFORM"


def pin_jax_platform(platform: str = "") -> str:
    """Pin jax to `platform` (or $TZ_JAX_PLATFORM when empty).
    Returns the platform requested ("" = no pin).  Must run before
    the first jax computation in the process."""
    platform = platform or os.environ.get(ENV_VAR, "")
    if not platform:
        return ""
    import jax

    jax.config.update("jax_platforms", platform)
    backend = jax.default_backend()
    if backend != platform:
        from syzkaller_tpu.utils import log

        log.logf(0, "WARNING: jax backend is %r despite %s=%r — the "
                    "pin ran after a backend initialized and was "
                    "silently ignored", backend, ENV_VAR, platform)
    return platform
