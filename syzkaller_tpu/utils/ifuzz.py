"""Machine-code generation/mutation for `text` buffer args.

x86 is table-driven: utils/x86.py holds a declarative opcode-map table
(one-byte map, 0F/0F38/0F3A maps, VEX, VMX/SVM), a structural
generator, an instruction-length decoder, and pseudo system sequences
— the same capability set as the reference's pkg/ifuzz (reference:
pkg/ifuzz/ifuzz.go:14-40 Insn model, generated/insns.go table,
pseudo.go sequences, decode via x86 length rules).  ARM64 stays a raw
byte generator, matching the reference's arm64 treatment
(reference: prog/rand.go:323-330).
"""

from __future__ import annotations

import random

from syzkaller_tpu.models.types import TextKind
from syzkaller_tpu.utils import x86

_MODE = {
    TextKind.X86_REAL: x86.REAL16,
    TextKind.X86_16: x86.PROT16,
    TextKind.X86_32: x86.PROT32,
    TextKind.X86_64: x86.LONG64,
}

DEFAULT_LEN = 10  # instructions per blob (reference: prog/rand.go:351)


def generate(kind: TextKind, r: random.Random) -> bytes:
    if kind == TextKind.ARM64:
        # Fixed-width 4-byte insns; random words are mostly decodable.
        return b"".join(r.randrange(1 << 32).to_bytes(4, "little")
                        for _ in range(12))
    cfg = x86.Config(mode=_MODE[kind], priv=True, avx=True,
                     len_insns=DEFAULT_LEN)
    return x86.generate(cfg, r)


def mutate(kind: TextKind, r: random.Random, text: bytes) -> bytes:
    if kind == TextKind.ARM64:
        data = bytearray(text)
        for _ in range(r.randrange(3) + 1):
            if not data or r.randrange(4) == 0:
                pos = r.randrange(len(data) // 4 + 1) * 4
                data[pos:pos] = r.randrange(1 << 32).to_bytes(4, "little")
            elif r.randrange(3) == 0 and len(data) >= 4:
                pos = r.randrange(len(data) // 4) * 4
                del data[pos:pos + 4]
            else:
                data[r.randrange(len(data))] = r.randrange(256)
        return bytes(data)
    cfg = x86.Config(mode=_MODE[kind], priv=True, avx=True)
    return x86.mutate(cfg, r, text)
