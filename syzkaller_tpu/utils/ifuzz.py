"""x86 machine-code generation/mutation for `text` buffer args.

The reference ships a ~100k-line generated x86 instruction table
consumed by pkg/ifuzz (reference: pkg/ifuzz/ifuzz.go:14-40) to fuzz KVM
guests.  We model the same interface with a compact generative encoder:
instructions are built from legal prefix/opcode/modrm/imm structure
plus interesting system instructions, rather than a full ISA table.
This keeps text-arg fuzzing structured (decodable prefixes, plausible
modrm forms) without a generated table; a full table-driven encoder is
a later milestone.
"""

from __future__ import annotations

import random

from syzkaller_tpu.models.types import TextKind

PREFIXES = [0x66, 0x67, 0xF0, 0xF2, 0xF3, 0x2E, 0x36, 0x3E, 0x26, 0x64, 0x65]

# A few "interesting" privileged/system instruction encodings that
# exercise VM exits and CPU state: hlt, cpuid, rdtsc, rdmsr, wrmsr,
# in/out, mov cr/dr, lgdt/lidt, invlpg, wbinvd, clts, sti/cli, iret,
# int3, int imm, sysenter/sysexit, vmcall-like.
SYSTEM_INSNS = [
    b"\xf4",              # hlt
    b"\x0f\xa2",          # cpuid
    b"\x0f\x31",          # rdtsc
    b"\x0f\x32",          # rdmsr
    b"\x0f\x30",          # wrmsr
    b"\xec",              # in al, dx
    b"\xee",              # out dx, al
    b"\x0f\x20\xc0",      # mov eax, cr0
    b"\x0f\x22\xc0",      # mov cr0, eax
    b"\x0f\x01\x10",      # lgdt [eax]
    b"\x0f\x01\x18",      # lidt [eax]
    b"\x0f\x01\x38",      # invlpg [eax]
    b"\x0f\x09",          # wbinvd
    b"\x0f\x06",          # clts
    b"\xfb",              # sti
    b"\xfa",              # cli
    b"\xcf",              # iret
    b"\xcc",              # int3
    b"\x0f\x34",          # sysenter
    b"\x0f\x35",          # sysexit
    b"\x0f\x01\xc1",      # vmcall
    b"\x0f\x01\xd9",      # vmmcall
]

DEFAULT_LEN = 10  # instructions per blob (reference: prog/rand.go:351)


def _gen_insn(mode: TextKind, r: random.Random) -> bytes:
    choice = r.randrange(10)
    if choice == 0:
        return SYSTEM_INSNS[r.randrange(len(SYSTEM_INSNS))]
    out = bytearray()
    # Optional legacy prefixes.
    while r.randrange(3) == 0 and len(out) < 4:
        out.append(PREFIXES[r.randrange(len(PREFIXES))])
    if mode == TextKind.X86_64 and r.randrange(3) == 0:
        out.append(0x40 | r.randrange(16))  # REX
    # Opcode: 1-byte, 0F 2-byte, or 0F 38/3A 3-byte escape.
    esc = r.randrange(8)
    if esc == 0:
        out += bytes([0x0F, 0x38, r.randrange(256)])
    elif esc == 1:
        out += bytes([0x0F, 0x3A, r.randrange(256)])
    elif esc <= 3:
        out += bytes([0x0F, r.randrange(256)])
    else:
        out.append(r.randrange(256))
    # ModRM + optional SIB + displacement.
    if r.randrange(2) == 0:
        modrm = r.randrange(256)
        out.append(modrm)
        mod, rm = modrm >> 6, modrm & 7
        if mod != 3 and rm == 4:
            out.append(r.randrange(256))  # SIB
        if mod == 1:
            out.append(r.randrange(256))
        elif mod == 2 or (mod == 0 and rm == 5):
            out += r.randrange(1 << 32).to_bytes(4, "little")
    # Optional immediate.
    imm = r.randrange(4)
    if imm == 1:
        out.append(r.randrange(256))
    elif imm == 2:
        out += r.randrange(1 << 16).to_bytes(2, "little")
    elif imm == 3:
        out += r.randrange(1 << 32).to_bytes(4, "little")
    return bytes(out)


def generate(kind: TextKind, r: random.Random) -> bytes:
    if kind == TextKind.ARM64:
        # Stub parity with the reference (reference: prog/rand.go:323-330).
        return bytes(r.randrange(256) for _ in range(50))
    out = bytearray()
    for _ in range(DEFAULT_LEN):
        out += _gen_insn(kind, r)
    return bytes(out)


def mutate(kind: TextKind, r: random.Random, text: bytes) -> bytes:
    if kind == TextKind.ARM64:
        from syzkaller_tpu.models.mutation import mutate_data
        from syzkaller_tpu.models.rand import RandGen

        rng = RandGen(None, r)
        return bytes(mutate_data(rng, bytearray(text), 40, 60))
    data = bytearray(text)
    for _ in range(r.randrange(3) + 1):
        op = r.randrange(3)
        if op == 0 and data:  # splice new instruction in
            pos = r.randrange(len(data) + 1)
            data[pos:pos] = _gen_insn(kind, r)
        elif op == 1 and data:  # overwrite a byte
            data[r.randrange(len(data))] = r.randrange(256)
        elif data:  # cut a chunk
            n = min(len(data), r.randrange(8) + 1)
            pos = r.randrange(len(data) - n + 1)
            del data[pos:pos + n]
        else:
            data += _gen_insn(kind, r)
    return bytes(data)
