"""Strict JSON config loader.

Rejects unknown fields so config typos fail loudly instead of being
silently ignored (reference: pkg/config/config.go LoadFile/LoadData —
json decoder with DisallowUnknownFields semantics).  Targets are
dataclasses; nested dataclass fields recurse, `dict`-typed fields
accept arbitrary sub-objects (the VM-type blob pattern,
syz-manager/mgrconfig/mgrconfig.go:85-87).
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Any, Type, TypeVar, Union, get_args, get_origin

T = TypeVar("T")


class ConfigError(Exception):
    pass


def load_file(path: Union[str, Path], cls: Type[T]) -> T:
    try:
        raw = Path(path).read_text()
    except OSError as e:
        raise ConfigError(f"failed to read config {path}: {e}") from e
    return load_data(raw, cls)


def load_data(data: str, cls: Type[T]) -> T:
    try:
        obj = json.loads(_strip_comments(data))
    except json.JSONDecodeError as e:
        raise ConfigError(f"bad config syntax: {e}") from e
    if not isinstance(obj, dict):
        raise ConfigError("config must be a JSON object")
    return from_dict(obj, cls)


def from_dict(obj: dict, cls: Type[T], path: str = "") -> T:
    import typing

    if not dataclasses.is_dataclass(cls):
        raise ConfigError(f"{cls} is not a config dataclass")
    hints = typing.get_type_hints(cls)
    fields = {f.name: f for f in dataclasses.fields(cls)}
    kwargs: dict[str, Any] = {}
    for key, val in obj.items():
        name = key.replace("-", "_")
        f = fields.get(name)
        if f is None:
            raise ConfigError(f"unknown config field {path}{key!r}")
        kwargs[name] = _convert(val, hints.get(name, Any), f"{path}{key}.")
    try:
        return cls(**kwargs)  # type: ignore[return-value]
    except TypeError as e:  # missing required (defaultless) field
        raise ConfigError(f"bad config: {e}") from e


def _convert(val: Any, typ: Any, path: str) -> Any:
    origin = get_origin(typ)
    if origin is Union:
        args = [a for a in get_args(typ) if a is not type(None)]
        if val is None:
            return None
        return _convert(val, args[0], path) if args else val
    if dataclasses.is_dataclass(typ) and isinstance(val, dict):
        return from_dict(val, typ, path)
    if origin in (list, tuple) and isinstance(val, list):
        args = get_args(typ)
        inner = args[0] if args else Any
        return [_convert(v, inner, path) for v in val]
    return val


def _strip_comments(data: str) -> str:
    """Allow // line comments in configs for operator convenience."""
    out = []
    for line in data.splitlines():
        stripped = line.lstrip()
        if stripped.startswith("//"):
            continue
        out.append(line)
    return "\n".join(out)
