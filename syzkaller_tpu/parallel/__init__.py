"""Device-mesh parallelism: sharded fuzz step and collectives.

The reference scales by processes and RPC (SURVEY.md §2.10-2.11); here
the equivalent axes are a 2D jax.sharding.Mesh:

  'batch'  data parallelism over programs (the new core axis: the
           reference mutates one program at a time, proc.go:92-95)
  'cov'    the global coverage plane sharded across devices; novelty
           is a single psum collective, merge a pmax — replacing the
           reference's per-process Go signal maps merged over RPC
           (pkg/signal/signal.go:117, syz-manager/manager.go:997).
"""

from syzkaller_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    make_sharded_fuzz_step,
    shard_batch,
    shard_plane,
)
