"""2D-sharded fuzz step: batch-parallel mutation x sharded coverage.

Layout over Mesh(('batch', 'cov')):
  program tensors   sharded on 'batch', replicated on 'cov'
  coverage plane    sharded on 'cov',   replicated on 'batch'
  flag tables       fully replicated

Per step, each device mutates its batch shard, tests its local edges
against its cov shard of the plane, and the partial novelty masks are
combined with a psum over 'cov' (each folded bucket lives in exactly
one shard, so the sum is exact).  Merging accepted edges pmaxes the
plane over 'batch' so replicas stay identical.  Collectives ride ICI;
nothing crosses the host.

All sharded steps go through `parallel.compat.shard_map`, which
probes the running jax build at first use (native jax.shard_map ->
experimental shard_map -> nested-vmap emulation) — this module never
imports a shard_map API at load time.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax, random
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from syzkaller_tpu.ops import signal as dsig
from syzkaller_tpu.ops.mutate import _mutate_one
from syzkaller_tpu.parallel import compat


def _batch_spec(mesh: Mesh):
    """Partition spec for program tensors: over ('host','batch')
    jointly on a multi-host mesh, else 'batch' (single source for
    every sharded step in this module)."""
    return P(("host", "batch")) if "host" in mesh.axis_names \
        else P("batch")


def _global_shard_idx(mesh: Mesh):
    """Traced host-major global shard index for RNG decorrelation —
    must match _batch_spec's layout."""
    idx = lax.axis_index("batch")
    if "host" in mesh.axis_names:
        idx = idx + lax.axis_index("host") * mesh.shape["batch"]
    return idx


def make_mesh(devices: Optional[list] = None, cov: int = 1) -> Mesh:
    """Mesh with ('batch', 'cov') axes over the given devices."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    assert n % cov == 0, f"{n} devices not divisible by cov={cov}"
    arr = np.array(devices).reshape(n // cov, cov)
    return Mesh(arr, ("batch", "cov"))


def graph_cache_key(mesh: Mesh, rounds: int, plane_size: int,
                    mutant_bits: int) -> dict:
    """The static shape fields that determine a fused-step executable
    — the compile-cache key the CompileObservatory records for the
    `mesh.fused_step` family.  Defined next to the builder so the key
    and the traced shapes cannot drift apart: two calls with equal
    keys MUST reuse one executable; a rebuild at an equal key means
    the cache itself was lost (the storm detector's worst case)."""
    return {
        "devices": int(np.prod(list(mesh.shape.values()))),
        "axes": "x".join(f"{a}={n}" for a, n in mesh.shape.items()),
        "rounds": int(rounds),
        "plane_size": int(plane_size),
        "mutant_bits": int(mutant_bits),
    }


def make_host_mesh(devices: Optional[list] = None, hosts: int = 2,
                   cov: int = 1) -> Mesh:
    """Mesh with ('host', 'batch', 'cov') axes: the multi-host form.

    The outer 'host' axis maps to DCN; 'batch' x 'cov' to each host's
    ICI-connected chips.  Program tensors shard over ('host','batch')
    jointly — each host's fleet works its own corpus shard, exactly
    the reference's per-manager corpus partition — while the coverage
    plane shards over 'cov' WITHIN a host and replicates across
    hosts.  Cross-host plane agreement is a pmax over 'host': inline
    per step when the step is built with the 'host' axis present, or
    amortized over DCN via the separate plane_host_sync step
    (reference analog: hub corpus sync on a cadence, syz-hub)."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    assert n % (hosts * cov) == 0, \
        f"{n} devices not divisible by hosts*cov={hosts * cov}"
    arr = np.array(devices).reshape(hosts, n // (hosts * cov), cov)
    return Mesh(arr, ("host", "batch", "cov"))


def make_plane_host_sync(mesh: Mesh):
    """Jitted periodic cross-host coverage sync: pmax of each plane
    shard over the 'host' axis — the DCN collective a deployment runs
    every N batches instead of inline (the plane is idempotent
    max-merge state, so late syncs only delay dedup, never lose
    signal)."""
    def local(plane_l):
        return lax.pmax(plane_l, "host")

    return jax.jit(compat.shard_map(
        local, mesh=mesh, in_specs=(P("cov"),), out_specs=P("cov"),
        check_vma=False))


def shard_batch(mesh: Mesh, batch: dict) -> dict:
    """Place stacked program tensors batch-sharded on the mesh
    (over ('host','batch') jointly on a multi-host mesh)."""
    sh = NamedSharding(mesh, _batch_spec(mesh))
    return {k: jax.device_put(jnp.asarray(v), sh) for k, v in batch.items()}


def shard_plane(mesh: Mesh, plane) -> jax.Array:
    return jax.device_put(plane, NamedSharding(mesh, P("cov")))


def shard_engine_plane(mesh: Mesh, engine) -> jax.Array:
    """Place the production TriageEngine's signal plane cov-sharded on
    the mesh: the sharded fuzz step and the fuzzer's novelty
    pre-filter share ONE plane instead of duplicating 64 MB per
    consumer.  Feed step outputs back with engine.absorb_plane (valid
    only in the standalone mesh form — see its contract)."""
    return shard_plane(mesh, engine.share_plane())


def make_sharded_fuzz_step(mesh: Mesh, rounds: int = 4, plane_size: int = dsig.PLANE_SIZE):
    """Build the jitted, mesh-sharded full fuzz step.

    step(batch, plane, edges, nedges, prios, key, flag_vals, flag_counts)
      -> (mutated_batch, new_plane, new_counts)

    Semantics: triage the incoming coverage (edges come from the
    executor fleet for the *previous* batch), merge novel programs'
    edges into the plane, and mutate the batch for the next round —
    the device side of one fuzz-loop iteration
    (reference loop: syz-fuzzer/proc.go:66-98,230-247).
    """
    n_cov = mesh.shape["cov"]
    shard = plane_size // n_cov
    has_host = "host" in mesh.axis_names

    def local_step(batch, plane_l, edges, nedges, prios, key,
                   flag_vals, flag_counts):
        # --- triage: local novelty vs my plane shard ---
        cov_idx = lax.axis_index("cov")
        base = cov_idx.astype(jnp.int32) * shard
        idx = dsig.fold_hash(edges)
        local = (idx >= base) & (idx < base + shard)
        seen = plane_l[jnp.clip(idx - base, 0, shard - 1)]
        E = edges.shape[1]
        valid = jnp.arange(E)[None, :] < nedges[:, None]
        sentinel = plane_size + jnp.arange(E, dtype=jnp.int32)[None, :]
        didx = jnp.where(valid, idx, sentinel)
        uniq = dsig._unique_mask(didx)
        new_local = (seen < (prios[:, None] + 1)) & valid & local & uniq
        new_counts = lax.psum(new_local.sum(axis=1).astype(jnp.int32), "cov")

        # --- merge: novel programs' edges into my shard, pmax 'batch' ---
        accept = new_counts > 0
        contrib = valid & local & accept[:, None]
        val = jnp.where(contrib, prios[:, None] + 1, 0).astype(jnp.uint8)
        plane_l = plane_l.at[jnp.clip(idx - base, 0, shard - 1).reshape(-1)
                             ].max(val.reshape(-1))
        plane_l = lax.pmax(plane_l, "batch")
        if has_host:
            # Inline cross-host agreement (DCN pmax).  A deployment
            # trading DCN traffic for slightly-delayed dedup builds
            # the step on a host-free mesh per fleet and runs
            # make_plane_host_sync on a cadence instead — the
            # reference's hub-sync shape.  Same-step double-discovery
            # across hosts matches multi-manager reference behavior.
            plane_l = lax.pmax(plane_l, "host")

        # --- mutate my batch shard for the next round ---
        b = batch["kind"].shape[0]
        # decorrelate across (host x) batch shards
        key = random.fold_in(key, _global_shard_idx(mesh))
        keys = random.split(key, b)
        mutated = jax.vmap(
            lambda st, k: _mutate_one(st, k, flag_vals, flag_counts, rounds)
        )(batch, keys)
        return mutated, plane_l, new_counts

    batch_spec = _batch_spec(mesh)
    step = jax.jit(
        compat.shard_map(
            local_step, mesh=mesh,
            in_specs=(batch_spec, P("cov"), batch_spec, batch_spec,
                      batch_spec, P(), P(), P()),
            out_specs=(batch_spec, P("cov"), batch_spec),
            check_vma=False,
        ))
    return step


def make_sharded_pack_step(mesh: Mesh, spec=None, rounds: int = 4):
    """The production pipeline step sharded over 'batch': each device
    mutates its corpus-row shard, packs deltas, and pools payloads
    LOCALLY (ops/delta.py pack/pool), emitting one flat wire buffer
    per shard — the multi-chip form of DevicePipeline._step, where
    each chip feeds its own host-side assembler and executor fleet.

    step(batch, key, flag_vals, flag_counts, template_idx) -> uint8
    flat buffer whose shards each hold rows ++ pool for their local
    sub-batch (split with unshard_delta)."""
    from syzkaller_tpu.ops.delta import DeltaSpec, make_packer, make_pooler

    spec = spec or DeltaSpec()
    pack = make_packer(spec)

    def local(batch, key, flag_vals, flag_counts, tidx):
        b = batch["kind"].shape[0]
        key = random.fold_in(key, _global_shard_idx(mesh))
        keys = random.split(key, b)

        def one(st, k, i):
            m = _mutate_one(st, k, flag_vals, flag_counts, rounds)
            return pack(m, i)

        rows, payloads, needs = jax.vmap(one)(batch, keys, tidx)
        return make_pooler(spec, b)(rows, payloads, needs)

    bspec = _batch_spec(mesh)
    return jax.jit(compat.shard_map(
        local, mesh=mesh,
        in_specs=(bspec, P(), P(), P(), bspec),
        out_specs=bspec, check_vma=False))


def make_fused_mesh_step(mesh: Mesh, spec=None, rounds: int = 4,
                         plane_size: int = dsig.PLANE_SIZE,
                         mutant_bits: int = dsig.MUTANT_PLANE_BITS_DEFAULT):
    """The multi-chip fused drain: ONE launch over the mesh runs
    triage -> mutate -> emit(pack) -> mutant-plane dedup -> compact —
    the mesh form of DevicePipeline's fused step (ISSUE 10), with the
    signal plane AND the mutant novelty plane sharded over 'cov'.

    step(batch, plane, mplane, edges, nedges, prios, key,
         flag_vals, flag_counts, tidx)
      -> (rows, pool, n_used, n_novel, new_counts, plane, mplane)

    where rows are each shard's delta rows compacted novel-first,
    pool the claimed payload slots, n_used/n_novel int32[1] per shard
    (global shape [n_batch_shards]), and new_counts the per-program
    signal novelty of the INCOMING edges (the executor feedback for
    the previous batch, reference loop proc.go:66-98).

    Both novelty families ride a single psum over 'cov': the local
    partial signal counts and the local mutant-plane freshness are
    stacked into one int32[2, b] operand, so the flush leader feeds N
    chips with exactly one collective before the merge pmaxes.  Each
    folded bucket (signal or mutant) is owned by exactly one 'cov'
    shard, so the sum is exact for both."""
    from syzkaller_tpu.ops.delta import (
        DeltaSpec,
        compact_rows,
        make_compact_pooler,
        make_packer,
    )

    spec = spec or DeltaSpec()
    pack = make_packer(spec)
    n_cov = mesh.shape["cov"]
    shard = plane_size // n_cov
    msize = 1 << mutant_bits
    mshard = msize // n_cov
    has_host = "host" in mesh.axis_names

    def local_step(batch, plane_l, mplane_l, edges, nedges, prios,
                   key, flag_vals, flag_counts, tidx):
        # --- triage incoming edges vs my signal-plane shard ---
        cov_idx = lax.axis_index("cov")
        base = cov_idx.astype(jnp.int32) * shard
        idx = dsig.fold_hash(edges)
        local = (idx >= base) & (idx < base + shard)
        seen = plane_l[jnp.clip(idx - base, 0, shard - 1)]
        E = edges.shape[1]
        valid = jnp.arange(E)[None, :] < nedges[:, None]
        sentinel = plane_size + jnp.arange(E, dtype=jnp.int32)[None, :]
        didx = jnp.where(valid, idx, sentinel)
        uniq = dsig._unique_mask(didx)
        new_local = (seen < (prios[:, None] + 1)) & valid & local & uniq
        sig_partial = new_local.sum(axis=1).astype(jnp.int32)

        # --- mutate + pack my batch shard (emit) ---
        b = batch["kind"].shape[0]
        key = random.fold_in(key, _global_shard_idx(mesh))
        keys = random.split(key, b)

        def one(st, k, i):
            return pack(_mutate_one(st, k, flag_vals, flag_counts,
                                    rounds), i)

        rows, payloads, needs = jax.vmap(one)(batch, keys, tidx)

        # --- mutant dedup vs my mutant-plane shard ---
        h = dsig.hash_rows(rows)
        midx = dsig.fold_mutant_idx(h, mutant_bits)
        mbase = cov_idx.astype(jnp.int32) * mshard
        mown = (midx >= mbase) & (midx < mbase + mshard)
        mfresh = (mplane_l[jnp.clip(midx - mbase, 0, mshard - 1)] == 0) \
            & mown

        # --- the single collective: both families, one psum ---
        combined = lax.psum(
            jnp.stack([sig_partial, mfresh.astype(jnp.int32)]), "cov")
        new_counts = combined[0]
        novel = combined[1] > 0

        # --- merge accepted edges into my shard; pmax over 'batch' ---
        accept = new_counts > 0
        contrib = valid & local & accept[:, None]
        val = jnp.where(contrib, prios[:, None] + 1, 0).astype(jnp.uint8)
        plane_l = plane_l.at[jnp.clip(idx - base, 0, shard - 1)
                             .reshape(-1)].max(val.reshape(-1))
        plane_l = lax.pmax(plane_l, "batch")
        # --- mark novel mutants' buckets; pmax over 'batch' ---
        mval = (novel & mown).astype(jnp.uint8)
        mplane_l = mplane_l.at[jnp.clip(midx - mbase, 0, mshard - 1)
                               ].max(mval)
        mplane_l = lax.pmax(mplane_l, "batch")
        if has_host:
            plane_l = lax.pmax(plane_l, "host")
            mplane_l = lax.pmax(mplane_l, "host")

        # --- emit-compact: claims on pre-compaction order, then the
        # novel-first prefix (non-novel rows never cross D2H) ---
        rows, pool_arr, n_used = make_compact_pooler(spec, b)(
            rows, payloads, needs & novel)
        rows, n_novel = compact_rows(rows, novel)
        return (rows, pool_arr, n_used.reshape(1), n_novel.reshape(1),
                new_counts, plane_l, mplane_l)

    bspec = _batch_spec(mesh)
    return jax.jit(compat.shard_map(
        local_step, mesh=mesh,
        in_specs=(bspec, P("cov"), P("cov"), bspec, bspec, bspec,
                  P(), P(), P(), bspec),
        out_specs=(bspec, bspec, bspec, bspec, bspec,
                   P("cov"), P("cov")),
        check_vma=False))


def unshard_delta(flat: np.ndarray, mesh: Mesh, spec=None) -> list:
    """Split a sharded pack-step result into per-shard DeltaBatch
    views (each shard's rows ++ pool block is self-contained)."""
    from syzkaller_tpu.ops.delta import DeltaBatch, DeltaSpec

    spec = spec or DeltaSpec()
    n = mesh.shape["batch"] * mesh.shape.get("host", 1)
    flat = np.asarray(flat)
    per = flat.size // n
    return [DeltaBatch(flat[i * per:(i + 1) * per], spec)
            for i in range(n)]
