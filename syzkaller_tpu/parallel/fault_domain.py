"""Fault-domain mesh engine: per-shard health + graceful chip loss.

The single-device pipeline became self-healing in PR 1 (breaker +
watchdog + host-snapshot rebuild) and the control plane in PR 8; this
module gives the multi-chip mesh the same discipline.  Every chip of
the ('batch','cov') mesh is its own FAULT DOMAIN — a per-shard
`health.CircuitBreaker` and `health.Watchdog` registered per device —
and the engine degrades gracefully instead of dying with the chip:

  demote      a failed collective launch triggers a per-shard probe
              sweep (`mesh.shard_probe` seam, shards probed in index
              order so a fault plan can script exactly which chip is
              "dead"); a blamed shard's breaker records the failure
              and, once it OPENS, the shard is demoted.
  re-shard    the fused mutate→emit-compact→novel_any graph is
              rebuilt over the surviving N−1 devices, and BOTH device
              planes are re-uploaded cov-sharded from host authority:
              the uint8[2^26] signal plane from the exact host mirror
              (the PR 4 rebuild path, now shard-aware — the mirror is
              merged on host at every accept, so chip loss loses zero
              signal), the TZ_MUTANT_PLANE_BITS mutant plane from its
              cadence-synced mirror (dedup-only state: staleness
              re-admits a few duplicates, never loses work).
  conserve    the staged batch is host-owned until its launch
              completes, so in-flight work on the dead shard simply
              re-dispatches with the retry onto the survivors — zero
              lost corpus programs.
  re-promote  a demoted shard's breaker goes half-open after backoff;
              a successful probe re-admits the chip and re-shards the
              planes back up to the full mesh.

Jitted step graphs are cached per live-topology, so the demote →
serve-from-N−1 → re-promote cycle compiles exactly the two expected
meshes and steady state adds zero new jits (pinned by the tier-1
compile-count guard).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import random
from jax.sharding import NamedSharding, PartitionSpec as P

from syzkaller_tpu import telemetry
from syzkaller_tpu.health import CircuitBreaker, Watchdog, fault_point
from syzkaller_tpu.health.envsafe import env_float, env_int
from syzkaller_tpu.ops import signal as dsig
from syzkaller_tpu.parallel import mesh as pmesh
from syzkaller_tpu.utils import log

_M_LIVE = telemetry.gauge(
    "tz_mesh_devices_live", "devices currently serving in the mesh")
_M_DEMOTED = telemetry.gauge(
    "tz_mesh_devices_demoted", "devices demoted out of the mesh")
_M_DEMOTE = telemetry.counter(
    "tz_mesh_demote_total", "shard demotions (breaker-open chip loss)")
_M_REPROMOTE = telemetry.counter(
    "tz_mesh_repromote_total", "shard re-admissions after half-open probe")
_M_RESHARD = telemetry.counter(
    "tz_mesh_reshard_total", "plane re-shards (topology rebuilds)")
_M_RESHARD_TS = telemetry.gauge(
    "tz_mesh_last_reshard_ts", "wallclock of the last plane re-shard")
_M_STEPS = telemetry.counter(
    "tz_mesh_steps_total", "fused mesh steps completed")

#: breaker state -> tz_mesh_shard_breaker_state gauge value
_BREAKER_STATE_CODE = {"closed": 0, "half_open": 1, "open": 2}


class ShardDomain:
    """One chip's fault domain: device + breaker + watchdog."""

    __slots__ = ("index", "device", "breaker", "watchdog", "demoted",
                 "demote_ts", "last_error", "_state_gauge")

    def __init__(self, index: int, device, breaker: CircuitBreaker,
                 watchdog: Watchdog):
        self.index = index
        self.device = device
        self.breaker = breaker
        self.watchdog = watchdog
        self.demoted = False
        self.demote_ts: Optional[float] = None
        self.last_error: Optional[str] = None
        self._state_gauge = telemetry.gauge(
            "tz_mesh_shard_breaker_state",
            "per-shard breaker state (0=closed 1=half_open 2=open)",
            labels={"shard": str(index)})

    def publish(self) -> None:
        self._state_gauge.set(
            _BREAKER_STATE_CODE.get(self.breaker.state, 2))

    def snapshot(self) -> dict:
        return {
            "index": self.index,
            "device": str(self.device),
            "demoted": self.demoted,
            "breaker": self.breaker.snapshot(),
            "last_error": self.last_error,
        }


class MeshEngine:
    """The fault-domain multi-chip drain (see module docstring).

    step(batch, edges, nedges, prios) runs one fused launch over the
    live mesh and returns per-shard novel delta rows + the signal
    verdicts for the incoming edges.  All device state is a cache of
    host authority, so any subset of chips can die between (or
    during) steps without losing corpus or signal.
    """

    def __init__(self, devices=None, cov: Optional[int] = None,
                 rounds: int = 4, spec=None,
                 plane_size: int = dsig.PLANE_SIZE,
                 mutant_bits: Optional[int] = None,
                 breaker_threshold: Optional[int] = None,
                 max_retries: int = 3, mutant_sync_every: int = 16,
                 flags=None, seed: int = 0, clock=time.monotonic):
        from syzkaller_tpu.ops.delta import DeltaSpec
        from syzkaller_tpu.ops.tensor import FlagTables

        if flags is None:
            flags = FlagTables.empty()
        self._flags = (np.asarray(flags.vals), np.asarray(flags.counts))

        if devices is None:
            devices = list(jax.devices())
            want = env_int("TZ_MESH_DEVICES", 0)
            if want > 0:
                devices = devices[:want]
        if not devices:
            raise ValueError("MeshEngine needs at least one device")
        self._cov_req = max(1, env_int("TZ_MESH_COV", 1)
                            if cov is None else cov)
        self.rounds = rounds
        self.spec = spec or DeltaSpec()
        self.plane_size = plane_size
        self.mutant_bits = (dsig.resolve_mutant_plane_bits()
                            if mutant_bits is None else int(mutant_bits))
        self.max_retries = max(1, max_retries)
        self._clock = clock
        self._key = random.key(seed)
        self._step_no = 0

        threshold = max(1, env_int("TZ_BREAKER_THRESHOLD", 4)
                        if breaker_threshold is None
                        else breaker_threshold)
        # Per-shard watchdog deadline: TZ_MESH_WATCHDOG_DEADLINE_S
        # overrides independently of the single-device pipeline's
        # TZ_WATCHDOG_DEADLINE_S (a collective launch waits on the
        # slowest chip, so mesh deployments often want more headroom).
        deadline = env_float(
            "TZ_MESH_WATCHDOG_DEADLINE_S",
            env_float("TZ_WATCHDOG_DEADLINE_S", 30.0))
        self.domains = [
            ShardDomain(i, dev,
                        CircuitBreaker(failure_threshold=threshold,
                                       seed=seed + i),
                        Watchdog(deadline_s=deadline))
            for i, dev in enumerate(devices)]
        # Leader watchdog bounding the collective launch itself.
        self.watchdog = Watchdog(
            deadline_s=deadline,
            compile_deadline_s=env_float("TZ_WATCHDOG_COMPILE_S", 600.0))

        # Host authority the re-shard rebuilds from: the signal-plane
        # mirror is EXACT (merged on host at every accept), the
        # mutant-plane mirror is cadence-synced (dedup-only state).
        self._mirror = np.zeros(plane_size, dtype=np.uint8)
        self._mmirror = np.zeros(1 << self.mutant_bits, dtype=np.uint8)
        self._mutant_sync_every = max(1, mutant_sync_every)
        self._steps_since_msync = 0

        self._lock = threading.RLock()
        self._graphs: dict = {}  # live-topology key -> (mesh, step)
        self._compiled_keys: set = set()
        self._plane_dev = None
        self._mplane_dev = None
        self._last_reshard: Optional[float] = None
        self.triage = None
        # Corpus arena (ISSUE 18, ops/arena): when attached, every
        # topology rebuild re-stages the arena slabs from HOST
        # authority, row-sharded over the 'batch' mesh axis — chip
        # loss costs device residency, never corpus rows.
        self._arena = None
        self._arena_dev = None
        self._hbm_arena = telemetry.HBM.register(
            "mesh", "arena", bound_to=self)
        # Residency ledger (ISSUE 17): the cov-sharded device planes
        # and their host-authority mirrors are the mesh's long-lived
        # footprint; updated at every re-shard / step absorb.
        self._hbm_planes = telemetry.HBM.register(
            "mesh", "planes", bound_to=self)
        self._hbm_mirrors = telemetry.HBM.register(
            "mesh", "mirrors", [self._mirror, self._mmirror],
            device="host", bound_to=self)
        self._build()

    # -- topology ---------------------------------------------------------

    def _live(self) -> list:
        return [d for d in self.domains if not d.demoted]

    def _fit_cov(self, n: int) -> int:
        c = min(self._cov_req, n)
        while c > 1 and (n % c or self.plane_size % c
                         or (1 << self.mutant_bits) % c):
            c -= 1
        return max(1, c)

    def _build(self) -> None:
        live = self._live()
        if not live:
            raise RuntimeError("mesh engine has no live devices left")
        key = tuple(d.index for d in live)
        entry = self._graphs.get(key)
        if entry is None:
            devs = [d.device for d in live]
            m = pmesh.make_mesh(devs, self._fit_cov(len(devs)))
            # Observatory compile point (ISSUE 17): a _graphs miss IS
            # a build of this topology's fused step — noted here (not
            # in parallel/mesh.py) so fault drills that stub the
            # builder still land in the ledger.
            with telemetry.COMPILES.observe(
                    "mesh.fused_step",
                    pmesh.graph_cache_key(
                        m, self.rounds, self.plane_size,
                        self.mutant_bits)):
                step = pmesh.make_fused_mesh_step(
                    m, spec=self.spec, rounds=self.rounds,
                    plane_size=self.plane_size,
                    mutant_bits=self.mutant_bits)
            entry = self._graphs[key] = (m, step)
            telemetry.COMPILES.set_cache_size(
                "mesh.fused_step", len(self._graphs))
        self._mesh, self._step_fn = entry
        self._topology_key = key
        for d in live:
            telemetry.SHARD_PROFILER.ensure(d.index)
        # Re-shard both planes from host authority, cov-sharded over
        # the (possibly shrunken) live mesh.
        sh = NamedSharding(self._mesh, P("cov"))
        self._plane_dev = jax.device_put(jnp.asarray(self._mirror), sh)
        self._mplane_dev = jax.device_put(jnp.asarray(self._mmirror), sh)
        self._hbm_planes.update([self._plane_dev, self._mplane_dev])
        self._reshard_arena()
        self._last_reshard = self._clock()
        _M_RESHARD.inc()
        _M_RESHARD_TS.set(time.time())
        _M_LIVE.set(len(live))
        _M_DEMOTED.set(len(self.domains) - len(live))
        for d in self.domains:
            d.publish()
        telemetry.record_event(
            "mesh.reshard",
            f"live={len(live)}/{len(self.domains)} cov="
            f"{self._mesh.shape['cov']}")

    # -- integration ------------------------------------------------------

    def attach_arena(self, arena) -> None:
        """Register a pipeline's corpus arena (ISSUE 18): its device
        slabs become part of this mesh's fault domain.  At every
        topology rebuild the occupied rows re-stage from the arena's
        HOST authority, row-sharded over the 'batch' axis, and the
        owning pipeline's slab copy is invalidated so its next flush
        is the one-scatter epoch rebuild — zero lost corpus under
        chip loss (test_mesh_faults pins the row-count conservation).
        """
        with self._lock:
            self._arena = arena
            self._reshard_arena()

    def _reshard_arena(self) -> None:
        arena = self._arena
        if arena is None or arena.host is None:
            return
        # Whole-slab re-stage from host authority (a copy, so the
        # device_put never aliases the mutable authority arrays).
        # Slab capacity is pow2 (ops/arena slab_capacity): it divides
        # any pow2 live width, but a demote can leave an odd width
        # (8 -> 7), so fall back to replication there — residency
        # costs more for the degraded interval, rows are never lost.
        rows = arena.authority_rows(np.arange(arena.capacity))
        width = int(self._mesh.shape["batch"])
        spec = P("batch") if arena.capacity % width == 0 else P()
        sh = NamedSharding(self._mesh, spec)
        self._arena_dev = {k: jax.device_put(jnp.asarray(v), sh)
                           for k, v in rows.items()}
        self._hbm_arena.update(list(self._arena_dev.values()))
        # The owning pipeline's own slab copy lived on the same
        # (possibly shrunken) device set: epoch-bump it so the next
        # pipeline flush re-uploads from the same host authority.
        arena.invalidate()

    def attach_triage(self, engine) -> None:
        """Co-use the production TriageEngine's host mirror as this
        engine's signal authority seed; push local discoveries back
        with sync_triage()."""
        self.triage = engine
        with self._lock:
            np.maximum(self._mirror, engine.mirror_copy(),
                       out=self._mirror)
            self._build()

    def sync_triage(self) -> None:
        """Merge this engine's signal authority into the attached
        triage engine (idempotent max-merge)."""
        if self.triage is not None:
            self.triage.absorb_plane(self._mirror)

    # -- the fused step ---------------------------------------------------

    def step(self, batch: dict, edges, nedges, prios,
             template_idx=None) -> dict:
        """One fused mesh launch over the staged batch; retries over
        rebuilt (possibly degraded) topologies until it lands.  The
        batch stays host-owned until a launch succeeds, so a chip
        death mid-flight conserves all staged work."""
        with self._lock:
            self._try_repromote()
            step_key = random.fold_in(self._key, self._step_no)
            self._step_no += 1
            attempts = 0
            while True:
                try:
                    fault_point("device.launch")
                    with telemetry.span("mesh.step"):
                        out = self._attempt(batch, edges, nedges,
                                            prios, template_idx,
                                            step_key)
                    break
                except Exception as e:  # noqa: BLE001 — attributed below
                    attempts += 1
                    blamed = self._attribute(e)
                    resharded = self._demote_opened()
                    if resharded:
                        self._build()
                    if attempts >= self.max_retries + len(self.domains):
                        raise
                    if not blamed and not resharded \
                            and attempts >= self.max_retries:
                        raise
                    log.logf(1, "mesh step retry %d after %r "
                                "(blamed=%s resharded=%s)",
                             attempts, e,
                             [d.index for d in blamed], resharded)
            self._absorb_success(out)
            return out

    def _pad(self, n_batch: int, batch, edges, nedges, prios,
             template_idx):
        B = int(np.asarray(nedges).shape[0])
        pad = (-B) % n_batch
        tidx = np.arange(B, dtype=np.int32) if template_idx is None \
            else np.asarray(template_idx, dtype=np.int32)
        if pad:
            def padrow(a):
                a = np.asarray(a)
                return np.concatenate(
                    [a, np.repeat(a[:1], pad, axis=0)], axis=0)

            batch = {k: padrow(v) for k, v in batch.items()}
            edges = padrow(edges)
            prios = padrow(prios)
            tidx = padrow(tidx)
            # Pad rows carry zero edges, so they can never merge
            # signal; their mutant rows are sliced off below.
            nedges = np.concatenate(
                [np.asarray(nedges),
                 np.zeros(pad, dtype=np.asarray(nedges).dtype)])
        return B, batch, edges, nedges, prios, tidx

    def _attempt(self, batch, edges, nedges, prios, template_idx,
                 step_key) -> dict:
        m = self._mesh
        n_batch = m.shape["batch"]
        B, batch_p, edges_p, nedges_p, prios_p, tidx = self._pad(
            n_batch, batch, edges, nedges, prios, template_idx)
        fv = jnp.asarray(self._flags[0])
        fc = jnp.asarray(self._flags[1])

        def launch():
            out = self._step_fn(
                {k: jnp.asarray(v) for k, v in batch_p.items()},
                self._plane_dev, self._mplane_dev,
                jnp.asarray(edges_p), jnp.asarray(nedges_p),
                jnp.asarray(prios_p), step_key, fv, fc,
                jnp.asarray(tidx))
            # The sync point: per-shard novel counts gate everything
            # the host fetches, exactly like the fused pipeline drain.
            jax.block_until_ready(out[3])
            return out

        first = self._topology_key not in self._compiled_keys
        t0 = self._clock()
        rows, pool_arr, n_used, n_novel, new_counts, plane, mplane = \
            self.watchdog.call(launch, "mesh.launch", compile=first)
        if not first:
            # A collective launch completes at the pace of its
            # slowest chip, so every live shard shares the batch's
            # host-observed residency (bench --profile isolates
            # per-chip probes for the differentiated view).
            elapsed = self._clock() - t0
            live = self._live()
            for d in live:
                telemetry.SHARD_PROFILER.note(d.index, elapsed)
            # Accounting ledger (ISSUE 14): a collective consumes
            # `elapsed` on EVERY live chip — total chip-time is
            # elapsed * width, split evenly across the shards.
            telemetry.ACCOUNTING.note_batch(
                elapsed * len(live),
                shard_rows={str(d.index): 1 for d in live})
        self._compiled_keys.add(self._topology_key)

        n_novel_np = np.asarray(n_novel)
        n_used_np = np.asarray(n_used)
        Bp = int(np.asarray(nedges_p).shape[0])
        per = Bp // n_batch
        pool_slots = self.spec.pool_slots(per)
        novel_rows, pool_blocks = [], []
        for s in range(n_batch):
            k = int(n_novel_np[s])
            novel_rows.append(np.asarray(rows[s * per:s * per + k]))
            u = int(n_used_np[s])
            pool_blocks.append(np.asarray(
                pool_arr[s * pool_slots:s * pool_slots + u]))
        return {
            "novel_rows": novel_rows,
            "pool_blocks": pool_blocks,
            "n_novel": n_novel_np,
            "n_used": n_used_np,
            "new_counts": np.asarray(new_counts)[:B],
            "_planes": (plane, mplane),
            "_inputs": (np.asarray(edges_p)[:B],
                        np.asarray(nedges_p)[:B],
                        np.asarray(prios_p)[:B], B),
        }

    def _absorb_success(self, out: dict) -> None:
        plane, mplane = out.pop("_planes")
        self._plane_dev, self._mplane_dev = plane, mplane
        self._hbm_planes.update([plane, mplane])
        edges, nedges, prios, B = out.pop("_inputs")
        # Exact host-mirror merge of the accepted programs' edges —
        # the merge the device just did, replayed on the authority,
        # so a later re-shard rebuilds the identical plane.
        accept = out["new_counts"] > 0
        if accept.any():
            E = edges.shape[1]
            valid = (np.arange(E)[None, :] < nedges[:, None]) \
                & accept[:, None]
            idx = dsig.fold_hash_np(edges[valid])
            np.maximum.at(self._mirror, idx,
                          (np.repeat(prios, E).reshape(B, E)[valid]
                           + 1).astype(np.uint8))
        # Cadence-synced mutant-plane mirror (dedup-only state).
        self._steps_since_msync += 1
        if self._steps_since_msync >= self._mutant_sync_every:
            self.sync_mutant_mirror()
        for d in self._live():
            d.breaker.record_success()
            d.publish()
        _M_STEPS.inc()

    def sync_mutant_mirror(self) -> None:
        """Pull the device mutant plane into its host mirror (best
        effort: a dying chip mid-fetch just leaves the mirror stale,
        which only re-admits duplicates)."""
        try:
            self._mmirror = np.asarray(self._mplane_dev)
            self._steps_since_msync = 0
            self._hbm_mirrors.update([self._mirror, self._mmirror],
                                     device="host")
        except Exception as e:  # noqa: BLE001
            log.logf(1, "mutant-mirror sync failed (stale mirror "
                        "kept): %r", e)

    # -- failure attribution / demote / re-promote ------------------------

    def _probe(self, dom: ShardDomain) -> None:
        """Tiny device round-trip pinning liveness of ONE chip.  The
        `mesh.shard_probe` seam fires once per probed shard in index
        order, so occurrence-indexed fault plans script exactly which
        chip is dead."""
        fault_point("mesh.shard_probe")
        x = jax.device_put(np.int32(dom.index), dom.device)
        if int(x) != dom.index:
            raise RuntimeError(f"probe mismatch on shard {dom.index}")

    def _attribute(self, exc: Exception) -> list:
        """Per-shard probe sweep after a failed collective launch."""
        blamed = []
        for dom in self._live():
            try:
                dom.watchdog.call(lambda d=dom: self._probe(d),
                                  "mesh.shard_probe")
            except Exception as e:  # noqa: BLE001
                dom.last_error = repr(e)
                dom.breaker.record_failure()
                blamed.append(dom)
            dom.publish()
        if not blamed:
            log.logf(1, "mesh launch failed but every shard probe "
                        "passed (transient collective failure): %r",
                     exc)
        return blamed

    def _demote_opened(self) -> bool:
        changed = False
        for dom in self._live():
            if dom.breaker.is_open():
                dom.demoted = True
                dom.demote_ts = self._clock()
                changed = True
                _M_DEMOTE.inc()
                telemetry.record_event(
                    "mesh.shard_demote",
                    f"shard={dom.index} device={dom.device} "
                    f"err={dom.last_error}")
                log.logf(0, "mesh shard %d demoted (%s)", dom.index,
                         dom.last_error)
        return changed

    def _try_repromote(self) -> bool:
        changed = False
        for dom in self.domains:
            if not dom.demoted or not dom.breaker.allow():
                continue
            dom.breaker.consume_rebuild()
            try:
                dom.watchdog.call(lambda d=dom: self._probe(d),
                                  "mesh.shard_probe")
            except Exception as e:  # noqa: BLE001
                dom.last_error = repr(e)
                dom.breaker.record_failure()
                dom.publish()
                continue
            dom.breaker.record_success()
            dom.demoted = False
            dom.demote_ts = None
            changed = True
            _M_REPROMOTE.inc()
            telemetry.record_event(
                "mesh.shard_repromote",
                f"shard={dom.index} device={dom.device}")
            log.logf(0, "mesh shard %d re-admitted", dom.index)
        if changed:
            # Freshen the mutant mirror from the surviving mesh
            # before re-sharding back up, then rebuild at full width.
            self.sync_mutant_mirror()
            self._build()
        return changed

    # -- introspection ----------------------------------------------------

    def mirror_plane(self) -> np.ndarray:
        """The signal-plane host authority (read-only view for tests
        and parity checks)."""
        return self._mirror

    def health_snapshot(self) -> dict:
        with self._lock:
            live = self._live()
            return {
                "devices_total": len(self.domains),
                "devices_live": len(live),
                "devices_demoted": len(self.domains) - len(live),
                "cov": int(self._mesh.shape["cov"]),
                "compat_impl": _compat_impl_name(),
                "last_reshard_age_s": (
                    None if self._last_reshard is None
                    else round(self._clock() - self._last_reshard, 3)),
                "arena_rows": (0 if self._arena is None
                               else self._arena.n),
                "arena_sharded": self._arena_dev is not None,
                "shards": [d.snapshot() for d in self.domains],
            }


def _compat_impl_name() -> str:
    from syzkaller_tpu.parallel import compat

    return compat.impl_name()
