"""shard_map compat shim: one `shard_map` entry point for every jax
build this repo meets.

The mesh engine was written against the modern `jax.shard_map` API
(`check_vma=`), which older builds don't carry — historically the
source of the 7-failure tier-1 floor (`jax.experimental.shard_map`
module present under a different call signature, top-level symbol
absent).  This module probes, in order, at FIRST USE (never at import,
so merely importing `parallel.mesh` can't fail on any build):

  1. `jax.shard_map`                       -> "native"
  2. `jax.experimental.shard_map.shard_map`-> "experimental"
     (check_vma is translated to the old check_rep flag)
  3. jit + nested `vmap(axis_name=...)` +
     `with_sharding_constraint`            -> "emulated"

Level 3 is a genuine semantic fallback, not a stub: `jax.vmap` with an
`axis_name` gives `lax.psum`/`lax.pmax`/`lax.axis_index` exactly the
per-shard view shard_map would, so any per-shard function whose specs
partition leading dimensions runs bit-exact — XLA's GSPMD partitioner
(steered by the output sharding constraints) decides device placement
instead of the explicit SPMD lowering.  TZ_MESH_COMPAT=native|
experimental|emulated|auto pins a level for debugging and for the
tier-1 test that proves the emulation agrees with the selected impl.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from syzkaller_tpu.health import envsafe
from syzkaller_tpu.utils import log

_lock = threading.Lock()
_impl: Optional[str] = None


def _probe() -> str:
    forced = envsafe.env_choice(
        "TZ_MESH_COMPAT", "auto",
        ("auto", "native", "experimental", "emulated"))
    if forced != "auto":
        return forced
    if callable(getattr(jax, "shard_map", None)):
        return "native"
    try:
        from jax.experimental.shard_map import shard_map as _sm  # noqa: F401
        has_experimental = True
    except Exception:
        has_experimental = False
    # Builds old enough to lack jax.shard_map pair the experimental
    # API with an SPMD partitioner that hard-aborts (not raises) when
    # lowering our collective step for multi-device CPU — the probe
    # cannot survive a test compile, so steer by backend: accelerator
    # backends take the real SPMD lowering, CPU takes the bit-exact
    # nested-vmap emulation.
    if has_experimental and jax.default_backend() != "cpu":
        return "experimental"
    return "emulated"


def impl_name() -> str:
    """The selected implementation ("native"|"experimental"|
    "emulated"), probing on first call."""
    global _impl
    with _lock:
        if _impl is None:
            _impl = _probe()
            log.logf(1, "parallel.compat: shard_map impl = %s", _impl)
        return _impl


def reset_impl() -> None:
    """Drop the cached probe result (tests flip TZ_MESH_COMPAT)."""
    global _impl
    with _lock:
        _impl = None


def shard_map(f, mesh: Mesh, in_specs, out_specs, check_vma: bool = False):
    """Compat `shard_map(f, mesh=..., in_specs=..., out_specs=...)`.

    Specs may partition only leading dimensions (every use in
    `parallel/mesh.py` shards dim 0 or nothing) and each in/out spec
    applies to the whole pytree of its argument/result — the prefix
    form the mesh module uses.
    """
    impl = impl_name()
    if impl == "native":
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    if impl == "experimental":
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(f, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_rep=check_vma)
    return _emulated_shard_map(f, mesh, in_specs, out_specs)


# --- level 3: nested-vmap emulation ---------------------------------

def _dim0_names(spec) -> tuple:
    """Mesh axis names partitioning a spec's dim 0, in spec (major to
    minor) order; () for replicated."""
    if spec is None:
        return ()
    parts = tuple(spec)
    if not parts or parts[0] is None:
        return ()
    p0 = parts[0]
    return tuple(p0) if isinstance(p0, tuple) else (p0,)


def _emulated_shard_map(f, mesh: Mesh, in_specs, out_specs):
    axis_names = tuple(mesh.axis_names)
    axis_size = {a: mesh.shape[a] for a in axis_names}

    def wrapped(*args):
        if len(args) != len(in_specs):
            raise TypeError(
                f"expected {len(in_specs)} args, got {len(args)}")
        # Flatten each arg subtree; its spec applies to every leaf.
        leaves, treedefs, leaf_axes = [], [], []
        for arg, spec in zip(args, in_specs):
            ls, td = jax.tree_util.tree_flatten(arg)
            names = _dim0_names(spec)
            for leaf in ls:
                leaves.append(_split_leaf(jnp.asarray(leaf), names,
                                          axis_size, axis_names))
                leaf_axes.append(frozenset(names))
            treedefs.append((td, len(ls)))

        def call_local(*flat):
            rebuilt, i = [], 0
            for td, n in treedefs:
                rebuilt.append(jax.tree_util.tree_unflatten(
                    td, list(flat[i:i + n])))
                i += n
            return f(*rebuilt)

        # Nested vmap, outermost mesh axis first; out_axes=0
        # everywhere, so outputs carry one leading dim per mesh axis
        # in mesh order.
        g = call_local
        for name in reversed(axis_names):
            in_axes = tuple(0 if name in ax else None for ax in leaf_axes)
            g = jax.vmap(g, in_axes=in_axes, out_axes=0,
                         axis_name=name, axis_size=axis_size[name])
        out = g(*leaves)

        # Reassemble each output subtree per its spec.  P subclasses
        # tuple, so a bare spec must not be mistaken for a spec list.
        out_tuple = isinstance(out_specs, (tuple, list)) \
            and not isinstance(out_specs, P)
        outs = out if out_tuple else (out,)
        specs = tuple(out_specs) if out_tuple else (out_specs,)
        merged = tuple(
            jax.tree_util.tree_map(
                lambda leaf, spec=spec: _merge_leaf(
                    leaf, _dim0_names(spec), axis_names, mesh, spec)
                , sub)
            for sub, spec in zip(outs, specs))
        return merged if out_tuple else merged[0]

    return wrapped


def _split_leaf(x, names, axis_size, axis_names):
    """Reshape dim 0 into one leading dim per sharding mesh axis
    (reordered into mesh-axis order for the nested vmap)."""
    if not names:
        return x
    sizes = [axis_size[n] for n in names]
    total = 1
    for s in sizes:
        total *= s
    if x.shape[0] % total:
        raise ValueError(
            f"dim 0 of shape {x.shape} not divisible by mesh extent "
            f"{total} for axes {names}")
    x = x.reshape(tuple(sizes) + (x.shape[0] // total,) + x.shape[1:])
    # spec order -> mesh order for the leading dims
    order = sorted(range(len(names)),
                   key=lambda i: axis_names.index(names[i]))
    if order != list(range(len(names))):
        x = jnp.transpose(
            x, tuple(order) + tuple(range(len(names), x.ndim)))
    return x

def _merge_leaf(leaf, names, axis_names, mesh, spec):
    """Invert _split_leaf on an output carrying one leading dim per
    mesh axis: drop replicated axes (any index — the function made
    them identical), merge sharded ones into dim 0 in spec order."""
    n_mesh = len(axis_names)
    keep = [i for i, a in enumerate(axis_names) if a in names]
    idx = tuple(slice(None) if i in keep else 0 for i in range(n_mesh))
    leaf = leaf[idx]
    # leading dims now follow mesh order; put them in spec order
    mesh_order = [a for a in axis_names if a in names]
    order = [mesh_order.index(n) for n in names]
    if order != list(range(len(names))):
        leaf = jnp.transpose(
            leaf, tuple(order) + tuple(range(len(names), leaf.ndim)))
    if names:
        leaf = leaf.reshape((-1,) + leaf.shape[len(names) + 1:])
    try:
        leaf = jax.lax.with_sharding_constraint(
            leaf, NamedSharding(mesh, spec if spec is not None else P()))
    except Exception:
        pass  # outside jit on some builds; placement is advisory here
    return leaf
