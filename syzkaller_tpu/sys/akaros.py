"""akaros/amd64 target: POSIX-compat model + arch hooks (model-only;
see sys/descriptions/akaros/sys.txt).  Akaros mmap takes the Linux
argument shape, so the memory-setup factory mirrors the linux one
(reference: sys/akaros/init.go)."""

from __future__ import annotations

from syzkaller_tpu.models.prog import (
    Call,
    ConstArg,
    PointerArg,
    make_return_arg,
)
from syzkaller_tpu.models.target import Target, register_lazy_target


def build_akaros_target(register: bool = False) -> Target:
    from syzkaller_tpu.models.target import register_target
    from syzkaller_tpu.sys.sysgen import compile_os, load_os_consts

    res = compile_os("akaros", "amd64", register=False)
    t = res.target
    t.string_dictionary = ["file0", "file1", "dir0"]
    k = load_os_consts("akaros")
    mmap_meta = next(c for c in t.syscalls if c.name == "mmap")
    prot = k.get("PROT_READ", 1) | k.get("PROT_WRITE", 2)
    mflags = (k.get("MAP_ANONYMOUS", 32) | k.get("MAP_PRIVATE", 2)
              | k.get("MAP_FIXED", 16))

    def make_mmap(addr: int, size: int) -> Call:
        a = [
            PointerArg.make_vma(mmap_meta.args[0], addr, size),
            ConstArg(mmap_meta.args[1], size),
            ConstArg(mmap_meta.args[2], prot),
            ConstArg(mmap_meta.args[3], mflags),
            ConstArg(mmap_meta.args[4], 0xFFFFFFFFFFFFFFFF),
            ConstArg(mmap_meta.args[5], 0),
        ]
        return Call(meta=mmap_meta, args=a,
                    ret=make_return_arg(mmap_meta.ret))

    t.make_mmap = make_mmap

    def sanitize(c: Call) -> None:
        if c.meta.call_name == "kill":
            sig = c.args[-1]
            if isinstance(sig, ConstArg) and sig.val in (9, 19):
                sig.val = 0

    t.sanitize = sanitize
    if register:
        register_target(t)
    return t


register_lazy_target("akaros", "amd64", build_akaros_target)
