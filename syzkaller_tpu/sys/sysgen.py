"""sysgen: the build-time driver of the description compiler.

The reference renders compiled descriptions into generated Go tables
with a revision hash and registers them at import (reference:
sys/syz-sysgen/sysgen.go:36-80, sys/<os>/gen/<arch>.go,
prog.RegisterTarget).  Here descriptions ship as syzlang sources under
sys/descriptions/<os>/ with per-arch .const files; targets are
compiled on first GetTarget and cached, and each Target carries the
revision (sha1 of its sources) so corpus databases can detect
description drift (reference: prog/target.go Revision field,
syz-manager/manager.go:192-207 re-minimization policy on mismatch).
"""

from __future__ import annotations

import hashlib
import os
from pathlib import Path
from typing import Optional

from syzkaller_tpu.models.target import register_lazy_target

DESC_ROOT = Path(__file__).parent / "descriptions"


def list_description_oses(root: Path = DESC_ROOT) -> list[str]:
    if not root.is_dir():
        return []
    return sorted(p.name for p in root.iterdir() if p.is_dir())


def description_arches(os_name: str, root: Path = DESC_ROOT) -> list[str]:
    """Arches are discovered from <name>_<arch>.const file suffixes."""
    arches = set()
    for p in (root / os_name).glob("*_*.const"):
        arches.add(p.stem.rsplit("_", 1)[1])
    return sorted(arches)


def load_os_consts(os_name: str, arch: str = "amd64",
                   root: Path = DESC_ROOT) -> dict[str, int]:
    """The merged const dict of an OS tree for one arch — the same
    files compile_os feeds the Compiler, for arch-hook modules that
    need individual values (mmap prot bits, sanitize tables, ...)."""
    from syzkaller_tpu.compiler.consts import load_const_files

    return load_const_files(
        str(p) for p in sorted((root / os_name).glob(f"*_{arch}.const")))


def revision_hash(os_name: str, root: Path = DESC_ROOT) -> str:
    h = hashlib.sha1()
    for p in sorted((root / os_name).glob("*")):
        h.update(p.name.encode())
        h.update(p.read_bytes())
    return h.hexdigest()


def compile_os(os_name: str, arch: str, root: Path = DESC_ROOT,
               register: bool = False):
    # Deferred import: sys/__init__ imports this module, and the
    # compiler imports sys.builder.
    from syzkaller_tpu.compiler.compile import Compiler
    from syzkaller_tpu.compiler.consts import load_const_files
    from syzkaller_tpu.compiler.parser import parse_glob

    src_files = sorted((root / os_name).glob("*.txt"))
    const_files = sorted((root / os_name).glob(f"*_{arch}.const"))
    desc = parse_glob(src_files)
    consts = load_const_files(str(p) for p in const_files)
    ptr_size = 4 if arch in ("32", "386", "arm") else 8
    # Strictness is a property of the const set itself: a real-kernel
    # description set ships a genuine syscall-number table (hundreds
    # of __NR_ entries), where a missing entry means the arch lacks
    # the call and it must compile disabled.  Hermetic sets (test/dsl,
    # unit-test fixtures with a stray __NR_) auto-number instead.
    nr_entries = sum(1 for k in consts if k.startswith("__NR_"))
    c = Compiler(desc, consts, os_name, arch, ptr_size=ptr_size,
                 strict_nr=nr_entries >= 50)
    res = c.compile(register=register)
    res.target.revision = revision_hash(os_name, root)
    return res


def register_all(root: Path = DESC_ROOT) -> list[tuple[str, str]]:
    """Register every shipped description target lazily; returns the
    (os, arch) pairs made available.  OSes whose arch-hook module
    already registered them (e.g. linux via sys/linux.py) are
    skipped."""
    from syzkaller_tpu.models.target import is_registered

    pairs = []
    for os_name in list_description_oses(root):
        for arch in description_arches(os_name, root):
            if is_registered(os_name, arch):
                continue
            register_lazy_target(
                os_name, arch,
                lambda o=os_name, a=arch: compile_os(o, a, root,
                                                     register=False).target)
            pairs.append((os_name, arch))
    return pairs


def main(argv: Optional[list[str]] = None) -> int:
    """CLI: report every compilable (os, arch) and its revision, the
    moral equivalent of `make generate` (reference: Makefile:187-196)."""
    import argparse

    ap = argparse.ArgumentParser(prog="sysgen")
    ap.add_argument("--root", default=str(DESC_ROOT))
    args = ap.parse_args(argv)
    root = Path(args.root)
    for os_name in list_description_oses(root):
        for arch in description_arches(os_name, root):
            res = compile_os(os_name, arch, root)
            t = res.target
            print(f"{os_name}/{arch}: {len(t.syscalls)} syscalls, "
                  f"{len(t.resources)} resources, rev {t.revision[:12]}"
                  + (f", disabled: {len(res.disabled_calls)}"
                     if res.disabled_calls else ""))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
