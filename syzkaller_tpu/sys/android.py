"""android targets: the linux model plus the ION staging surface.

The reference's android tree is exactly this shape — linux
descriptions with sys/android/ion.txt layered on top (reference:
sys/android/, the only description set there).  Here the compiler
merges sys/descriptions/linux/*.txt with sys/descriptions/android/
ion.txt under one namespace, so ION's openat$ion reuses linux's
open_flags and the resulting target runs on any linux host executor
(the ioctls just fail cleanly where /dev/ion does not exist).
"""

from __future__ import annotations

from syzkaller_tpu.models.target import Target, register_lazy_target


def build_android_target(register: bool = False,
                         arch: str = "amd64") -> Target:
    from syzkaller_tpu.compiler.compile import Compiler
    from syzkaller_tpu.compiler.parser import parse_glob
    from syzkaller_tpu.models.target import register_target
    from syzkaller_tpu.sys.linux import _attach_arch_hooks, _load_consts
    from syzkaller_tpu.sys.sysgen import (DESC_ROOT, load_os_consts,
                                          revision_hash)

    src = sorted((DESC_ROOT / "linux").glob("*.txt")) \
        + sorted((DESC_ROOT / "android").glob("*.txt"))
    consts = {**load_os_consts("linux", arch),
              **load_os_consts("android", arch)}
    c = Compiler(parse_glob(src), consts, "android", arch, ptr_size=8,
                 strict_nr=True)
    res = c.compile(register=False)
    t = res.target
    t.revision = revision_hash("linux") + "+" + revision_hash("android")
    _attach_arch_hooks(t, _load_consts(arch))
    if register:
        register_target(t)
    return t


register_lazy_target("android", "amd64",
                     lambda: build_android_target(arch="amd64"))
register_lazy_target("android", "arm64",
                     lambda: build_android_target(arch="arm64"))
