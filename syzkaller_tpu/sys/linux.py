"""linux/amd64 target: syzlang descriptions + arch hooks.

The syscall surface is compiled from sys/descriptions/linux/*.txt
with values from linux_amd64.const (produced by sys/extract against
host headers — the `make extract` step).  This module is the arch-hook
layer the reference keeps in sys/linux/init.go:40-149: the mmap call
factory, call sanitization that neutralizes dangerous arguments, and
the string dictionary for buffer generation.
"""

from __future__ import annotations

from pathlib import Path

from syzkaller_tpu.models.prog import (
    Call,
    ConstArg,
    PointerArg,
    make_return_arg,
)
from syzkaller_tpu.models.target import Target, register_lazy_target


def _load_consts(arch: str = "amd64") -> dict[str, int]:
    from syzkaller_tpu.compiler.consts import load_const_files
    from syzkaller_tpu.sys.sysgen import DESC_ROOT

    return load_const_files(
        str(p) for p in sorted((DESC_ROOT / "linux").glob(f"*_{arch}.const")))


def build_linux_target(register: bool = False, arch: str = "amd64") -> Target:
    from syzkaller_tpu.models.target import register_target
    from syzkaller_tpu.sys.sysgen import compile_os

    res = compile_os("linux", arch, register=False)
    t = res.target
    _attach_arch_hooks(t, _load_consts(arch))
    if register:
        register_target(t)
    return t


def build_linux_arm64_target(register: bool = False) -> Target:
    """linux/arm64: same descriptions, arm64's own syscall-number table
    (generic unistd) — legacy x86-only calls (open, fork, epoll_wait,
    ...) are compiled disabled, as on the reference's arm64 target
    (reference: sys/linux/gen/arm64.go built from per-arch .const)."""
    return build_linux_target(register=register, arch="arm64")


def build_linux_386_target(register: bool = False) -> Target:
    """linux/386: 32-bit pointers (sysgen pins ptr_size=4) and the
    i386 syscall table from <asm/unistd_32.h> (sys/extract.extract_386
    two-pass); amd64-only entries compile disabled (reference:
    sys/linux/gen/386.go built from per-arch .const)."""
    return build_linux_target(register=register, arch="386")


def _attach_arch_hooks(t: Target, k: dict[str, int]) -> None:
    t.string_dictionary = [
        "/dev/null", "/dev/zero", "/dev/full", "/proc/self/exe",
        "/proc/self/fd", "lo", "eth0", "sit0", "syz_tun", "./file0",
        "./file1", "cgroup",
    ]

    mmap_meta = next(c for c in t.syscalls if c.name == "mmap")
    prot = k.get("PROT_READ", 1) | k.get("PROT_WRITE", 2)
    mflags = (k.get("MAP_ANONYMOUS", 0x20) | k.get("MAP_PRIVATE", 2)
              | k.get("MAP_FIXED", 0x10))

    def make_mmap(addr: int, size: int) -> Call:
        a = [
            PointerArg.make_vma(mmap_meta.args[0], addr, size),
            ConstArg(mmap_meta.args[1], size),
            ConstArg(mmap_meta.args[2], prot),
            ConstArg(mmap_meta.args[3], mflags),
            ConstArg(mmap_meta.args[4], 0xFFFFFFFFFFFFFFFF),
            ConstArg(mmap_meta.args[5], 0),
        ]
        return Call(meta=mmap_meta, args=a,
                    ret=make_return_arg(mmap_meta.ret))

    t.make_mmap = make_mmap

    sigkill = k.get("SIGKILL", 9)
    sigstop = k.get("SIGSTOP", 19)
    s_ifmt = k.get("S_IFMT", 0o170000)
    s_ifchr = k.get("S_IFCHR", 0o020000)
    s_ifblk = k.get("S_IFBLK", 0o060000)
    harmless_dev = 0x700  # LOOP_MAJOR << 8

    def sanitize(c: Call) -> None:
        """Neutralize calls that would kill/wedge the fuzzer itself
        (reference: sys/linux/init.go sanitizeCall, :100-148)."""
        name = c.meta.call_name
        if name in ("kill", "tkill", "tgkill"):
            sig = c.args[-1]  # sig is the last arg of all three
            if isinstance(sig, ConstArg) and sig.val in (sigkill, sigstop):
                sig.val = 0
        elif name in ("mknod", "mknodat"):
            mode_i, dev_i = (1, 2) if name == "mknod" else (2, 3)
            if len(c.args) > dev_i:
                mode = c.args[mode_i]
                dev = c.args[dev_i]
                if isinstance(mode, ConstArg) and isinstance(dev, ConstArg) \
                        and (mode.val & s_ifmt) in (s_ifchr, s_ifblk):
                    dev.val = harmless_dev
        elif name == "exit" or name == "exit_group":
            # Keep exit codes off the executor's reserved statuses;
            # the kernel truncates to 8 bits, so mask before checking.
            code = c.args[0] if c.args else None
            if isinstance(code, ConstArg) and (code.val & 0xFF) in (67, 68, 69):
                code.val = 1

    t.sanitize_call = sanitize


register_lazy_target("linux", "amd64", build_linux_target)
register_lazy_target("linux", "arm64", build_linux_arm64_target)
register_lazy_target("linux", "386", build_linux_386_target)
