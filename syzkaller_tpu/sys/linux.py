"""linux/amd64 description model (growing subset).

The reference describes the full Linux interface in 60+ syzlang files
(reference: sys/linux/*.txt).  We start from the core file/memory/net
surface — enough to drive a real executor end-to-end — and grow the
model over time; descriptions use real amd64 syscall numbers.

Arch hooks follow the reference's linux init
(reference: sys/linux/init.go:40-149): mmap call factory and call
sanitization neutralizing dangerous arguments.
"""

from __future__ import annotations

from syzkaller_tpu.models.prog import Call, ConstArg, PointerArg, make_return_arg
from syzkaller_tpu.models.types import Dir
from syzkaller_tpu.sys.builder import (
    TargetBuilder,
    array,
    buffer,
    bytesize_of,
    const,
    filename,
    flags,
    int16,
    int32,
    int64,
    intptr,
    len_of,
    opt,
    proc,
    ptr,
    res,
    string,
    vma,
)

# Constants extracted from the kernel ABI (values are part of the ABI,
# cf. the reference's .const files produced by syz-extract).
PROT_READ, PROT_WRITE, PROT_EXEC = 1, 2, 4
MAP_PRIVATE, MAP_ANONYMOUS, MAP_FIXED = 0x2, 0x20, 0x10
O_RDONLY, O_WRONLY, O_RDWR, O_CREAT, O_TRUNC, O_APPEND, O_NONBLOCK = (
    0, 1, 2, 0o100, 0o1000, 0o2000, 0o4000)
AF_UNIX, AF_INET, AF_INET6, AF_NETLINK = 1, 2, 10, 16
SOCK_STREAM, SOCK_DGRAM, SOCK_RAW, SOCK_SEQPACKET = 1, 2, 3, 5
SIGKILL = 9


def build_linux_target(register: bool = True):
    b = TargetBuilder(os="linux", arch="amd64", ptr_size=8, page_size=4096,
                      num_pages=4096)
    b.string_dictionary = ["/dev/null", "/proc/self", "lo", "eth0", "sit0"]

    b.flag_set("mmap_prot", PROT_READ, PROT_WRITE, PROT_EXEC)
    b.flag_set("mmap_flags", MAP_PRIVATE, MAP_ANONYMOUS, MAP_FIXED)
    b.flag_set("open_flags", O_RDONLY, O_WRONLY, O_RDWR, O_CREAT, O_TRUNC,
               O_APPEND, O_NONBLOCK)
    b.flag_set("socket_domain", AF_UNIX, AF_INET, AF_INET6, AF_NETLINK)
    b.flag_set("socket_type", SOCK_STREAM, SOCK_DGRAM, SOCK_RAW, SOCK_SEQPACKET)

    b.resource("fd", 4, values=(0xFFFFFFFFFFFFFFFF,))
    b.resource("sock", 4, values=(0xFFFFFFFFFFFFFFFF,), parent="fd")
    b.resource("pid", 4, values=(0,))

    # mmap is syscall 0 in the table (make_mmap depends on this
    # builder convention; the wire NR is the real one).
    b.syscall("mmap", [
        ("addr", vma()), ("len", len_of("addr")),
        ("prot", flags("mmap_prot")), ("flags", flags("mmap_flags")),
        ("fd", const(0xFFFFFFFFFFFFFFFF, 4)), ("offset", const(0, 8)),
    ], nr=9)
    b.syscall("open", [
        ("file", ptr(Dir.IN, filename())), ("flags", flags("open_flags")),
        ("mode", const(0o644, 4)),
    ], ret="fd", nr=2)
    b.syscall("openat", [
        ("fd", const(0xFFFFFFFFFFFFFF9C, 4)),  # AT_FDCWD
        ("file", ptr(Dir.IN, filename())), ("flags", flags("open_flags")),
        ("mode", const(0o644, 4)),
    ], ret="fd", nr=257)
    b.syscall("close", [("fd", res("fd"))], nr=3)
    b.syscall("read", [
        ("fd", res("fd")), ("buf", ptr(Dir.OUT, buffer())),
        ("count", len_of("buf")),
    ], nr=0)
    b.syscall("write", [
        ("fd", res("fd")), ("buf", ptr(Dir.IN, buffer())),
        ("count", bytesize_of("buf")),
    ], nr=1)
    b.syscall("lseek", [
        ("fd", res("fd")), ("offset", intptr(fileoff=True)),
        ("whence", flags("seek_whence", 4)),
    ], nr=8)
    b.flag_set("seek_whence", 0, 1, 2)
    b.syscall("dup", [("oldfd", res("fd"))], ret="fd", nr=32)
    b.syscall("dup2", [("oldfd", res("fd")), ("newfd", res("fd"))],
              ret="fd", nr=33)
    b.syscall("pipe", [("pipefd", ptr(Dir.OUT, "pipe_fds"))], nr=22)
    b.struct("pipe_fds", [("rfd", res("fd")), ("wfd", res("fd"))])
    b.syscall("socket", [
        ("domain", flags("socket_domain", 4)), ("type", flags("socket_type", 4)),
        ("proto", const(0, 4)),
    ], ret="sock", nr=41)
    b.struct("sockaddr_un", [
        ("family", const(AF_UNIX, 2)),
        ("path", filename(size=108)),
    ], packed=True)
    b.syscall("bind", [
        ("fd", res("sock")), ("addr", ptr(Dir.IN, "sockaddr_un")),
        ("addrlen", bytesize_of("addr", 4)),
    ], nr=49)
    b.syscall("listen", [("fd", res("sock")), ("backlog", int32())], nr=50)
    b.syscall("getpid", [], ret="pid", nr=39)
    b.syscall("kill", [("pid", res("pid")), ("sig", const(0, 4))], nr=62)
    b.syscall("munmap", [("addr", vma()), ("len", len_of("addr"))], nr=11)
    b.syscall("mprotect", [
        ("addr", vma()), ("len", len_of("addr")), ("prot", flags("mmap_prot")),
    ], nr=10)
    b.syscall("ioctl", [
        ("fd", res("fd")), ("cmd", intptr()), ("arg", opt(intptr())),
    ], nr=16)
    b.syscall("fcntl", [
        ("fd", res("fd")), ("cmd", int32(range=(0, 16))), ("arg", opt(intptr())),
    ], nr=72)
    b.syscall("fsync", [("fd", res("fd"))], nr=74)
    b.syscall("ftruncate", [("fd", res("fd")), ("len", intptr(fileoff=True))],
              nr=77)
    b.syscall("unlink", [("file", ptr(Dir.IN, filename()))], nr=87)
    b.syscall("mkdir", [
        ("file", ptr(Dir.IN, filename())), ("mode", const(0o755, 4)),
    ], nr=83)

    def sanitize(c: Call) -> None:
        # Neutralize dangerous calls (reference: sys/linux/init.go:100-148):
        # don't let the fuzzer kill arbitrary processes or mmap FIXED over
        # the program's own mappings at address 0.
        if c.meta.call_name == "kill" and len(c.args) >= 2:
            sig = c.args[1]
            if isinstance(sig, ConstArg) and sig.val == SIGKILL:
                sig.val = 0

    b.sanitize_call = sanitize

    def make_mmap(target, addr: int, size: int) -> Call:
        meta = target.syscalls[0]
        a = [
            PointerArg.make_vma(meta.args[0], addr, size),
            ConstArg(meta.args[1], size),
            ConstArg(meta.args[2], PROT_READ | PROT_WRITE),
            ConstArg(meta.args[3], MAP_ANONYMOUS | MAP_PRIVATE | MAP_FIXED),
            ConstArg(meta.args[4], 0xFFFFFFFFFFFFFFFF),
            ConstArg(meta.args[5], 0),
        ]
        return Call(meta=meta, args=a, ret=make_return_arg(meta.ret))

    b.make_mmap = make_mmap
    return b.build(register=register)


target = build_linux_target()
