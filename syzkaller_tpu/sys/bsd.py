"""Shared arch-hook machinery for the BSD model targets.

FreeBSD and NetBSD share the hook shape (MAP_ANON|MAP_PRIVATE|
MAP_FIXED mmap with fd -1, kill-signal sanitizing); each OS module
parameterizes this builder instead of copying it (the role the
reference's per-OS init.go files play, factored once).
"""

from __future__ import annotations

from syzkaller_tpu.models.prog import (
    Call,
    ConstArg,
    PointerArg,
    make_return_arg,
)
from syzkaller_tpu.models.target import Target


def load_bsd_consts(os_name: str) -> dict[str, int]:
    from syzkaller_tpu.sys.sysgen import load_os_consts

    return load_os_consts(os_name)


def make_bsd_target_builder(os_name: str, string_dictionary: list[str],
                            kill_signals: tuple[int, ...] = (9, 17)):
    """Returns a build_<os>_target(register=False) factory."""

    def build(register: bool = False) -> Target:
        from syzkaller_tpu.models.target import register_target
        from syzkaller_tpu.sys.sysgen import compile_os

        res = compile_os(os_name, "amd64", register=False)
        t = res.target
        _attach_hooks(t, load_bsd_consts(os_name), string_dictionary,
                      kill_signals)
        if register:
            register_target(t)
        return t

    return build


def _attach_hooks(t: Target, k: dict[str, int],
                  string_dictionary: list[str],
                  kill_signals: tuple[int, ...]) -> None:
    t.string_dictionary = list(string_dictionary)

    mmap_meta = next(c for c in t.syscalls if c.name == "mmap")
    prot = k.get("PROT_READ", 1) | k.get("PROT_WRITE", 2)
    mflags = (k.get("MAP_ANON", 0x1000) | k.get("MAP_PRIVATE", 2)
              | k.get("MAP_FIXED", 0x10))

    def make_mmap(addr: int, size: int) -> Call:
        a = [
            PointerArg.make_vma(mmap_meta.args[0], addr, size),
            ConstArg(mmap_meta.args[1], size),
            ConstArg(mmap_meta.args[2], prot),
            ConstArg(mmap_meta.args[3], mflags),
            ConstArg(mmap_meta.args[4], 0xFFFFFFFFFFFFFFFF),
            ConstArg(mmap_meta.args[5], 0),
        ]
        return Call(meta=mmap_meta, args=a,
                    ret=make_return_arg(mmap_meta.ret))

    t.make_mmap = make_mmap

    def sanitize(c: Call) -> None:
        name = c.meta.call_name
        if name == "kill":
            sig = c.args[-1]
            if isinstance(sig, ConstArg) and sig.val in kill_signals:
                sig.val = 0
        elif name == "exit":
            code = c.args[0] if c.args else None
            if isinstance(code, ConstArg) \
                    and (code.val & 0xFF) in (67, 68, 69):
                code.val = 1

    t.sanitize_call = sanitize
