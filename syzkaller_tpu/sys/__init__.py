"""Syscall description models (the "model families" of the framework).

Each OS target is described either via the Python builder API
(sys/builder.py) or compiled from syzlang description files
(compiler/).  Importing this package registers the built-in targets:

  test/64   hermetic fake OS exercising every type-system feature
            (the unit-test target; reference: sys/test)
  linux/{amd64,arm64,386}  the linux model (2,062 syscall variants
            on amd64; arm64 (2,024) and 386 (2,051) compile the same
            descriptions against their own syscall-number tables and
            pointer widths)
  android/{amd64,arm64}  linux plus the ION staging surface
  freebsd/amd64  compact FreeBSD model (multi-OS machinery proof)
  netbsd/amd64   compact NetBSD model (model-only cross-OS target)
  dsl/64    syzlang-compiled fake OS (exercises the description
            pipeline; compiled lazily from sys/descriptions/dsl)
"""

from syzkaller_tpu.sys import testtarget  # noqa: F401  (registers test/64)
from syzkaller_tpu.sys import linux  # noqa: F401  (registers linux/amd64)
from syzkaller_tpu.sys import freebsd  # noqa: F401  (registers freebsd/amd64)
from syzkaller_tpu.sys import netbsd  # noqa: F401  (registers netbsd/amd64)
from syzkaller_tpu.sys import fuchsia  # noqa: F401  (registers fuchsia/amd64)
from syzkaller_tpu.sys import windows  # noqa: F401  (registers windows/amd64)
from syzkaller_tpu.sys import akaros  # noqa: F401  (registers akaros/amd64)
from syzkaller_tpu.sys import android  # noqa: F401  (android/{amd64,arm64})
from syzkaller_tpu.sys import sysgen

sysgen.register_all()
