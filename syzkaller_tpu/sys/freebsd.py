"""freebsd/amd64 target: syzlang descriptions + BSD arch hooks.

Second real-OS target proving the multi-OS machinery end to end
(descriptions + const tables + arch hooks + registry), the role the
reference's sys/freebsd tree + init.go plays.  Compiled from
sys/descriptions/freebsd/*.txt with ABI values from
freebsd_amd64.const (see that file's provenance note).
"""

from __future__ import annotations

from syzkaller_tpu.models.target import register_lazy_target
from syzkaller_tpu.sys.bsd import load_bsd_consts, make_bsd_target_builder


def _load_consts() -> dict[str, int]:
    return load_bsd_consts("freebsd")


build_freebsd_target = make_bsd_target_builder(
    "freebsd",
    string_dictionary=["/dev/null", "/dev/zero", "./file0", "./file1",
                       "lo0", "em0"],
    kill_signals=(9, 17))  # SIGKILL, SIGSTOP (BSD numbering)

register_lazy_target("freebsd", "amd64", build_freebsd_target)
