"""freebsd/amd64 target: syzlang descriptions + arch hooks.

Second real-OS target proving the multi-OS machinery end to end
(descriptions + const tables + arch hooks + registry), the role the
reference's sys/freebsd tree + init.go plays.  Compiled from
sys/descriptions/freebsd/*.txt with ABI values from
freebsd_amd64.const (see that file's provenance note).
"""

from __future__ import annotations

from syzkaller_tpu.models.prog import (
    Call,
    ConstArg,
    PointerArg,
    make_return_arg,
)
from syzkaller_tpu.models.target import Target, register_lazy_target


def _load_consts() -> dict[str, int]:
    from syzkaller_tpu.compiler.consts import load_const_files
    from syzkaller_tpu.sys.sysgen import DESC_ROOT

    return load_const_files(
        str(p)
        for p in sorted((DESC_ROOT / "freebsd").glob("*_amd64.const")))


def build_freebsd_target(register: bool = False) -> Target:
    from syzkaller_tpu.models.target import register_target
    from syzkaller_tpu.sys.sysgen import compile_os

    res = compile_os("freebsd", "amd64", register=False)
    t = res.target
    _attach_arch_hooks(t, _load_consts())
    if register:
        register_target(t)
    return t


def _attach_arch_hooks(t: Target, k: dict[str, int]) -> None:
    t.string_dictionary = [
        "/dev/null", "/dev/zero", "./file0", "./file1", "lo0", "em0",
    ]

    mmap_meta = next(c for c in t.syscalls if c.name == "mmap")
    prot = k.get("PROT_READ", 1) | k.get("PROT_WRITE", 2)
    # BSD anonymous mappings use MAP_ANON and fd -1
    mflags = (k.get("MAP_ANON", 0x1000) | k.get("MAP_PRIVATE", 2)
              | k.get("MAP_FIXED", 0x10))

    def make_mmap(addr: int, size: int) -> Call:
        a = [
            PointerArg.make_vma(mmap_meta.args[0], addr, size),
            ConstArg(mmap_meta.args[1], size),
            ConstArg(mmap_meta.args[2], prot),
            ConstArg(mmap_meta.args[3], mflags),
            ConstArg(mmap_meta.args[4], 0xFFFFFFFFFFFFFFFF),
            ConstArg(mmap_meta.args[5], 0),
        ]
        return Call(meta=mmap_meta, args=a,
                    ret=make_return_arg(mmap_meta.ret))

    t.make_mmap = make_mmap

    sigkill = 9
    sigstop = 17  # FreeBSD SIGSTOP

    def sanitize(c: Call) -> None:
        name = c.meta.call_name
        if name == "kill":
            sig = c.args[-1]
            if isinstance(sig, ConstArg) and sig.val in (sigkill, sigstop):
                sig.val = 0
        elif name == "exit":
            code = c.args[0] if c.args else None
            if isinstance(code, ConstArg) \
                    and (code.val & 0xFF) in (67, 68, 69):
                code.val = 1

    t.sanitize_call = sanitize


register_lazy_target("freebsd", "amd64", build_freebsd_target)
