"""The hermetic "test" OS: a synthetic syscall table exercising every
type-system feature with no kernel behind it.

This is the unit-test target for the whole framework, mirroring the
role of the reference's fake OS (reference: sys/test/test.txt,
sys/targets/targets.go:37-46): alignment/padding, bitfields, unions
(fixed and varlen), arrays, length fields in all units and paths,
endianness, vma, proc, strings, checksums, resources with subtyping,
recursion, and optional args.
"""

from __future__ import annotations

from syzkaller_tpu.models.types import CsumKind, Dir, TextKind
from syzkaller_tpu.sys.builder import (
    TargetBuilder,
    array,
    bitsize_of,
    blob_range,
    buffer,
    bytesize_of,
    const,
    csum,
    filename,
    flags,
    int8,
    int16,
    int32,
    int64,
    intptr,
    len_of,
    opt,
    proc,
    ptr,
    res,
    string,
    text,
    vma,
)

IPPROTO_TCP = 6
IPPROTO_UDP = 17


def build_test_target(register: bool = True):
    b = TargetBuilder(os="test", arch="64", ptr_size=8, page_size=4096,
                      num_pages=4096)
    b.string_dictionary = ["kernel", "fuzz", "tpu"]

    # mmap must be syscall 0 for make_mmap (see builder._default_make_mmap).
    b.syscall("tz_mmap", [("addr", vma()), ("len", len_of("addr"))])
    b.syscall("tz_nop", [])

    # -- integers ------------------------------------------------------
    b.syscall("tz_nop$ints", [
        ("a0", intptr()), ("a1", int8()), ("a2", int16()),
        ("a3", int32()), ("a4", int64()),
    ])
    b.syscall("tz_nop$ranges", [
        ("lo", int32(range=(0, 10))),
        ("hi", int64(range=(100, 1 << 40))),
        ("off", int64(fileoff=True)),
    ])
    b.syscall("tz_nop$be", [
        ("a0", int16(be=True)), ("a1", int32(be=True)), ("a2", int64(be=True)),
    ])

    # -- optional args -------------------------------------------------
    b.syscall("tz_opt$scalar", [("a0", opt(intptr()))])
    b.syscall("tz_opt$ptr", [("a0", ptr(Dir.IN, intptr(), opt=True))])
    b.syscall("tz_opt$vma", [("a0", vma(opt=True))])
    b.syscall("tz_opt$proc", [("a0", proc(100, 4, opt=True))])

    # -- alignment & padding -------------------------------------------
    b.struct("pad_natural", [
        ("p0", int16()), ("p1", int32()), ("p2", int8()),
        ("p3", int16()), ("p4", int64()),
    ])
    b.struct("pad_packed", [
        ("p0", int16()), ("p1", int32()), ("p2", int8()),
        ("p3", int16()), ("p4", int64()),
    ], packed=True)
    b.struct("pad_inner_packed", [("q0", array(int16(), 1))], packed=True)
    b.struct("pad_inner_plain", [("q0", array(int16(), 1))])
    b.struct("pad_mixed", [
        ("m0", int8()), ("m1", "pad_inner_packed"), ("m2", "pad_inner_plain"),
    ])
    b.struct("align_one", [("a0", int8())])
    b.struct("align_four", [("a0", int8())], align=4)
    b.struct("align_host", [
        ("h0", int8()), ("h1", "align_one"), ("h2", "align_four"),
    ])
    b.struct("packed_aligned", [("x0", int8()), ("x1", int16())],
             packed=True, align=4)
    b.struct("pa_host", [("y0", "packed_aligned"), ("y1", int8())])
    b.struct("tail_varlen", [("t0", int8()), ("t1", array(int32()))])
    b.syscall("tz_align$natural", [("a0", ptr(Dir.IN, "pad_natural"))])
    b.syscall("tz_align$packed", [("a0", ptr(Dir.IN, "pad_packed"))])
    b.syscall("tz_align$mixed", [("a0", ptr(Dir.IN, "pad_mixed"))])
    b.syscall("tz_align$attr", [("a0", ptr(Dir.IN, "align_host"))])
    b.syscall("tz_align$packed_aligned", [("a0", ptr(Dir.IN, "pa_host"))])
    b.syscall("tz_align$tail", [("a0", ptr(Dir.IN, "tail_varlen"))])

    # -- structs -------------------------------------------------------
    b.struct("nested_inner", [("i0", int8())])
    b.struct("nested_outer", [("o0", int64()), ("o1", "nested_inner")])
    b.syscall("tz_struct", [("a0", ptr(Dir.IN, "nested_outer"))])

    # -- unions --------------------------------------------------------
    b.union("u_fixed", [
        ("v0", int64()), ("v1", array(int64(), 10)), ("v2", int8()),
    ])
    b.struct("u_fixed_host", [("f", int64()), ("u", "u_fixed")])
    b.union("u_varlen", [("v0", int64()), ("v1", int32())], varlen=True)
    b.struct("u_varlen_host", [("u", "u_varlen"), ("tail", int8())], packed=True)
    b.union("u_arg", [
        ("w0", int8()), ("w1", int64()), ("w2", ptr(Dir.IN, int32())),
        ("w3", res("fd")), ("w4", const(1, 8)),
        ("w5", flags("len_flags", 4)), ("w6", proc(0, 1, 2)),
    ])
    b.syscall("tz_union$fixed", [("a0", ptr(Dir.IN, "u_fixed_host"))])
    b.syscall("tz_union$varlen", [("a0", ptr(Dir.IN, "u_varlen_host"))])
    b.syscall("tz_union$arg", [("a0", "u_arg")])

    # -- arrays --------------------------------------------------------
    b.union("arr_elem", [("e0", int16()), ("e1", int64())], varlen=True)
    b.struct("arr_mid", [
        ("r0", int8()), ("r1", array("arr_elem", (1, 2))), ("r2", int64()),
    ], packed=True)
    b.struct("arr_tail", [("r0", int8()), ("r1", array(int8(), (4, 8)))])
    b.struct("arr_fixed", [
        ("r0", int16()), ("r1", array(int8(), 16)), ("r2", int16()),
    ])
    b.syscall("tz_array$mid", [("a0", ptr(Dir.IN, "arr_mid"))])
    b.syscall("tz_array$tail", [("a0", ptr(Dir.IN, "arr_tail"))])
    b.syscall("tz_array$fixed", [("a0", ptr(Dir.IN, "arr_fixed"))])

    # -- length fields -------------------------------------------------
    b.flag_set("len_flags", 0, 1)
    b.struct("len_sibling", [("f0", int16()), ("f1", len_of("f0", 2))])
    b.struct("len_of_len", [
        ("f0", int32()), ("f1", len_of("f0", 2)), ("f2", len_of("f1", 2)),
    ])
    b.struct("len_mutual", [("f0", len_of("f1", 2)), ("f1", len_of("f0", 2))])
    b.struct("len_parent", [("f0", int16()), ("f1", len_of("parent", 2))])
    b.struct("len_array", [
        ("f0", array(int16(), 4)), ("f1", len_of("f0", 2)),
        ("f2", bytesize_of("f0", 2)),
    ])
    b.struct("len_units", [
        ("f0", array(int64(), 2)),
        ("f1", len_of("f0", 1)),
        ("f2", bytesize_of("f0", 1)),
        ("f3", bytesize_of("f0", 1, unit=2)),
        ("f4", bytesize_of("f0", 1, unit=4)),
        ("f5", bytesize_of("f0", 1, unit=8)),
    ])
    b.struct("len_deep_inner", [
        ("g0", int8()), ("g1", len_of("g0", 1)), ("g2", len_of("parent", 2)),
        ("g3", array(int32(), 3)),
    ])
    b.struct("len_deep", [
        ("f0", len_of("parent", 8)),
        ("f1", "len_deep_inner"),
        ("f2", array("len_deep_inner", 1)),
        ("f3", len_of("f1", 4)),
        ("f4", len_of("f2", 2)),
        ("f5", array(int16())),
    ])
    b.struct("len_named_inner2", [
        ("n1", len_of("parent", 1)),
        ("n2", len_of("len_named_inner2", 1)),
        ("n3", len_of("len_named_inner", 1)),
        ("n4", len_of("len_named", 1)),
    ])
    b.struct("len_named_inner", [
        ("n0", "len_named_inner2"),
        ("n1", len_of("parent", 1)),
        ("n2", len_of("len_named_inner", 1)),
        ("n3", len_of("len_named", 1)),
    ])
    b.struct("len_named", [
        ("n0", "len_named_inner"),
        ("n1", len_of("parent", 1)),
        ("n2", len_of("len_named", 1)),
    ])
    b.struct("len_vma", [("f0", vma()), ("f1", len_of("f0", 8))])
    b.struct("big_struct", [
        ("b0", int64()), ("b1", int64()), ("b2", array(int32(), 8)),
    ])
    b.syscall("tz_len$sibling", [("a0", ptr(Dir.IN, "len_sibling"))])
    b.syscall("tz_len$len_of_len", [("a0", ptr(Dir.IN, "len_of_len"))])
    b.syscall("tz_len$mutual", [("a0", ptr(Dir.IN, "len_mutual"))])
    b.syscall("tz_len$parent", [("a0", ptr(Dir.IN, "len_parent"))])
    b.syscall("tz_len$array", [("a0", ptr(Dir.IN, "len_array"))])
    b.syscall("tz_len$units", [("a0", ptr(Dir.IN, "len_units"))])
    b.syscall("tz_len$deep", [("a0", ptr(Dir.IN, "len_deep"))])
    b.syscall("tz_len$named", [("a0", ptr(Dir.IN, "len_named"))])
    b.syscall("tz_len$vma_struct", [("a0", ptr(Dir.IN, "len_vma"))])
    b.syscall("tz_len$of_arg", [("a0", int16()), ("a1", len_of("a0"))])
    b.syscall("tz_len$of_ptr", [
        ("a0", ptr(Dir.IN, "big_struct")), ("a1", len_of("a0")),
    ])
    b.syscall("tz_len$of_opt_ptr", [
        ("a0", ptr(Dir.IN, "big_struct", opt=True)), ("a1", len_of("a0")),
    ])
    b.syscall("tz_len$inout", [
        ("a0", ptr(Dir.INOUT, "big_struct")),
        ("a1", ptr(Dir.INOUT, len_of("a0", 8))),
    ])
    b.syscall("tz_len$vma", [
        ("v0", vma()), ("l0", len_of("v0")),
        ("b0", bytesize_of("v0", 8)), ("b2", bytesize_of("v0", 8, unit=2)),
    ])
    b.syscall("tz_len$bits", [
        ("a0", ptr(Dir.IN, int64())), ("a1", bitsize_of("a0")),
    ])
    b.syscall("tz_len$bits_arr", [
        ("a0", ptr(Dir.IN, array(int8()))), ("a1", bitsize_of("a0")),
    ])
    b.syscall("tz_len$arr_of_arr", [
        ("a0", ptr(Dir.IN, array(array(int8())))), ("a1", len_of("a0")),
    ])

    # -- bitfields -----------------------------------------------------
    b.flag_set("bf_flags", 0, 1, 2)
    b.struct("bf_primary", [
        ("c0", flags("bf_flags", 2, bits=10)),
        ("c1", int64()),
        ("c2", const(0x42, 2, bits=5)),
        ("c3", int16(bits=6)),
        ("c4", const(0x42, 4, bits=15)),
        ("c5", len_of("parent", 2, bits=11)),
        ("c6", len_of("parent", 2, be=True, bits=11)),
        ("c7", int8()),
    ])
    b.struct("bf_grouped_inner", [
        ("c0", int32(bits=10)), ("c1", int32(bits=10)), ("c2", int32(bits=10)),
    ])
    b.struct("bf_grouped", [("c0", "bf_grouped_inner"), ("c1", int8())])
    b.struct("bf_aligned", [
        ("c0", int8(bits=1)), ("c1", int8(bits=1)), ("c2", int8(bits=1)),
        ("c3", int16(bits=1)), ("c4", int16(bits=1)), ("c5", int16(bits=1)),
    ], packed=True, align=8)
    b.struct("bf_host", [("c0", "bf_aligned"), ("c1", int8())])
    b.struct("bf_len", [
        ("c0", int32(bits=10)), ("c1", int32(bits=10)), ("c2", int32(bits=10)),
        ("c3", int32(bits=32)), ("c4", int32(bits=16)), ("c5", int32(bits=16)),
        ("c6", int32(bits=10)), ("c7", len_of("parent", 4, bits=16)),
    ])
    b.struct("bf_len_host", [
        ("c0", "bf_len"), ("c1", len_of("c0", 1)), ("c2", bytesize_of("c0", 1)),
        ("c3", bytesize_of("c0", 1, unit=4)),
    ])
    b.syscall("tz_bf$primary", [("a0", ptr(Dir.IN, "bf_primary"))])
    b.syscall("tz_bf$grouped", [("a0", ptr(Dir.IN, "bf_grouped"))])
    b.syscall("tz_bf$aligned", [("a0", ptr(Dir.IN, "bf_host"))])
    b.syscall("tz_bf$len", [("a0", ptr(Dir.IN, "bf_len_host"))])

    # -- big endian structs --------------------------------------------
    b.flag_set("end_flags", 0, 1)
    b.struct("be_ints", [
        ("e0", int8()), ("e1", int16(be=True)), ("e2", int32(be=True)),
        ("e3", int64(be=True)),
    ], packed=True)
    b.struct("be_var", [
        ("e0", len_of("parent", 2, be=True)),
        ("e1", const(0x42, 4, be=True)),
        ("e2", flags("end_flags", 8, be=True)),
    ], packed=True)
    b.syscall("tz_be$ints", [("a0", ptr(Dir.IN, "be_ints"))])
    b.syscall("tz_be$var", [("a0", ptr(Dir.IN, "be_var"))])

    # -- vma -----------------------------------------------------------
    b.syscall("tz_vma", [
        ("v0", vma()), ("l0", len_of("v0")),
        ("v1", vma(range=(5, 5))), ("l1", len_of("v1")),
        ("v2", vma(range=(7, 9))), ("l2", len_of("v2")),
    ])

    # -- text ----------------------------------------------------------
    b.syscall("tz_text$x86_real", [
        ("a0", ptr(Dir.IN, text(TextKind.X86_REAL))), ("a1", len_of("a0")),
    ])
    b.syscall("tz_text$x86_64", [
        ("a0", ptr(Dir.IN, text(TextKind.X86_64))), ("a1", len_of("a0")),
    ])

    # -- buffers & strings ---------------------------------------------
    b.string_set("greet_strings", "hey", "folks")
    b.struct("str_sized", [
        ("s1", string("greet_strings", size=10)),
        ("s2", string("greet_strings", size=8)),
        ("b1", bytesize_of("s1", 1)),
        ("b2", bytesize_of("parent", 1)),
    ])
    b.struct("fname_fixed", [
        ("f1", filename(size=10)), ("f2", filename(size=20)),
        ("b1", bytesize_of("f1", 1)), ("b2", bytesize_of("f2", 1)),
        ("b3", bytesize_of("parent", 1)),
    ])
    b.syscall("tz_buf$blob", [("a0", ptr(Dir.IN, buffer()))])
    b.syscall("tz_buf$blob_range", [("a0", ptr(Dir.IN, blob_range(16, 64)))])
    b.syscall("tz_buf$out", [("a0", ptr(Dir.OUT, buffer())), ("a1", len_of("a0"))])
    b.syscall("tz_buf$str", [("a0", ptr(Dir.IN, string())), ("a1", len_of("a0"))])
    b.syscall("tz_buf$str_sized", [("a0", ptr(Dir.IN, "str_sized"))])
    b.syscall("tz_buf$fname", [
        ("path", ptr(Dir.IN, filename())), ("mode", flags("open_modes")),
    ])
    b.syscall("tz_buf$fname_fixed", [("a0", ptr(Dir.IN, "fname_fixed"))])
    b.flag_set("open_modes", 0xABABABABABABABAB, 0xCDCDCDCDCDCDCDCD)

    # -- checksums -----------------------------------------------------
    b.struct("csum_plain", [
        ("sum", csum("parent", CsumKind.INET, 0, 2)),
        ("src_ip", int32(be=True)), ("dst_ip", int32(be=True)),
    ], packed=True)
    b.struct("csum_pseudo_hdr", [
        ("sum", csum("csum_pseudo_pkt", CsumKind.PSEUDO, IPPROTO_TCP, 2)),
    ], packed=True)
    b.struct("csum_pseudo_pkt", [
        ("hdr", "csum_pseudo_hdr"), ("payload", array(int8())),
    ], packed=True)
    b.struct("csum_pseudo_host", [
        ("outer", "csum_plain"), ("inner", "csum_pseudo_pkt"),
    ], packed=True)
    b.syscall("tz_csum$inet", [("a0", ptr(Dir.IN, "csum_plain"))])
    b.syscall("tz_csum$pseudo", [("a0", ptr(Dir.IN, "csum_pseudo_host"))])

    # -- recursion -----------------------------------------------------
    b.struct("rec_self", [("a0", ptr(Dir.IN, "rec_self", opt=True))])
    b.struct("rec_a", [
        ("a0", ptr(Dir.IN, "rec_a", opt=True)),
        ("a1", ptr(Dir.IN, "rec_b", opt=True)),
    ])
    b.struct("rec_b", [
        ("b0", ptr(Dir.IN, "rec_self", opt=True)),
        ("b1", ptr(Dir.IN, "rec_a", opt=True)),
        ("b2", ptr(Dir.IN, "rec_b", opt=True)),
    ])
    b.syscall("tz_recur$self", [("a0", ptr(Dir.INOUT, "rec_self"))])
    b.syscall("tz_recur$mutual", [("a0", ptr(Dir.INOUT, "rec_b"))])

    # -- resources -----------------------------------------------------
    b.resource("fd", 4, values=(0xFFFFFFFFFFFFFFFF,))
    b.resource("token", 4, values=(0xFFFF,))
    b.resource("token_big", 4, values=(0xFFFF0000,), parent="token")
    b.syscall("tz_res$make", [], ret="token")
    b.syscall("tz_res$make_big", [], ret="token_big")
    b.syscall("tz_res$use", [("t", res("token"))])
    b.syscall("tz_res$use_big", [("t", res("token_big"))])
    b.syscall("tz_res$open", [("path", ptr(Dir.IN, filename()))], ret="fd")
    b.syscall("tz_res$close", [("f", res("fd"))])
    b.syscall("tz_res$write", [
        ("f", res("fd")), ("buf", ptr(Dir.IN, buffer())),
        ("n", bytesize_of("buf")),
    ])
    b.syscall("tz_res$out_arg", [("t", ptr(Dir.OUT, res("token")))])

    # -- proc ----------------------------------------------------------
    b.syscall("tz_proc", [("a0", proc(100, 4, 2))])

    # -- hints / mutation workhorses -----------------------------------
    b.syscall("tz_hint$data", [("a0", ptr(Dir.IN, array(int8())))])
    b.syscall("tz_mut$vec", [
        ("vec", ptr(Dir.IN, array(int32(range=(0, 1))))), ("vlen", len_of("vec")),
    ])
    b.syscall("tz_mut$blob", [
        ("data", ptr(Dir.IN, array(int8()))), ("size", bytesize_of("data")),
    ])
    b.syscall("tz_mut$fd_blob", [
        ("f", res("fd")), ("data", ptr(Dir.IN, array(int8()))),
        ("size", bytesize_of("data")),
    ])
    b.syscall("tz_mut$str", [("a0", ptr(Dir.IN, string())), ("a1", len_of("a0"))])
    b.syscall("tz_mut$proc", [("a0", proc(100, 4, opt=True))])

    # -- serialization corner cases ------------------------------------
    b.struct("out_inner", [("f0", buffer())])
    b.syscall("tz_ser$out_struct", [("a0", ptr(Dir.INOUT, "out_inner"))])
    b.syscall("tz_ser$out_arr", [
        ("a", ptr(Dir.OUT, array(int8()))), ("b", len_of("a")),
    ])
    b.struct("one_field", [("f1", int8())])
    b.union("one_union", [("f1", int8())])
    b.syscall("tz_ser$args0", [])
    b.syscall("tz_ser$args1", [("a1", int8())])
    b.syscall("tz_ser$fields", [("a1", ptr(Dir.IN, "one_field"))])
    b.syscall("tz_ser$union", [("a1", ptr(Dir.IN, "one_union"))])

    return b.build(register=register)


target = build_test_target()
