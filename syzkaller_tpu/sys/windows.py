"""windows/amd64 target: typed Win32 model + arch hooks.

Model-only on this host (no Windows runtime), like the reference's
sys/windows tree; see sys/descriptions/windows/sys.txt for
provenance.  The memory-setup factory is VirtualAlloc, Windows's
mmap (reference: sys/windows/init.go).
"""

from __future__ import annotations

from syzkaller_tpu.models.prog import (
    Call,
    ConstArg,
    PointerArg,
    make_return_arg,
)
from syzkaller_tpu.models.target import Target, register_lazy_target


def build_windows_target(register: bool = False) -> Target:
    from syzkaller_tpu.models.target import register_target
    from syzkaller_tpu.sys.sysgen import compile_os, load_os_consts

    res = compile_os("windows", "amd64", register=False)
    t = res.target
    t.string_dictionary = ["fuzz0.tmp", "fuzzdir", "Software\\Fuzz"]
    k = load_os_consts("windows")
    mmap_meta = next(c for c in t.syscalls if c.name == "VirtualAlloc")
    alloc = k.get("MEM_COMMIT", 0x1000) | k.get("MEM_RESERVE", 0x2000)
    prot = k.get("PAGE_READWRITE", 4)

    def make_mmap(addr: int, size: int) -> Call:
        a = [
            PointerArg.make_vma(mmap_meta.args[0], addr, size),
            ConstArg(mmap_meta.args[1], size),
            ConstArg(mmap_meta.args[2], alloc),
            ConstArg(mmap_meta.args[3], prot),
        ]
        return Call(meta=mmap_meta, args=a,
                    ret=make_return_arg(mmap_meta.ret))

    t.make_mmap = make_mmap
    if register:
        register_target(t)
    return t


register_lazy_target("windows", "amd64", build_windows_target)
