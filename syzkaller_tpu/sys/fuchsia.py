"""fuchsia/amd64 target: Zircon handle-centric model + arch hooks.

Model-only on this host (no Zircon kernel), like the reference's
cross-OS trees; see sys/descriptions/fuchsia/sys.txt for provenance.
The memory-setup factory maps a VMO through the root VMAR —
zx_vmar_map is Zircon's mmap (reference: sys/fuchsia/init.go).
"""

from __future__ import annotations

from syzkaller_tpu.models.prog import (
    Call,
    ConstArg,
    PointerArg,
    make_return_arg,
)
from syzkaller_tpu.models.target import Target, register_lazy_target


def build_fuchsia_target(register: bool = False,
                         arch: str = "amd64") -> Target:
    from syzkaller_tpu.models.target import register_target
    from syzkaller_tpu.sys.sysgen import compile_os

    res = compile_os("fuchsia", arch, register=False)
    t = res.target
    t.string_dictionary = ["fuzz", "proc0", "thr0"]
    from syzkaller_tpu.sys.sysgen import load_os_consts
    k = load_os_consts("fuchsia", arch)
    mmap_meta = next(c for c in t.syscalls if c.name == "zx_vmar_map")
    perm = (k.get("ZX_VM_PERM_READ", 1) | k.get("ZX_VM_PERM_WRITE", 2)
            | k.get("ZX_VM_SPECIFIC", 16))

    def make_mmap(addr: int, size: int) -> Call:
        a = [
            ConstArg(mmap_meta.args[0], 0),      # root vmar (handle 0)
            ConstArg(mmap_meta.args[1], perm),
            ConstArg(mmap_meta.args[2], addr),
            ConstArg(mmap_meta.args[3], 0),      # vmo handle
            ConstArg(mmap_meta.args[4], 0),
            ConstArg(mmap_meta.args[5], size),
            PointerArg.make_null(mmap_meta.args[6]),
        ]
        return Call(meta=mmap_meta, args=a,
                    ret=make_return_arg(mmap_meta.ret))

    t.make_mmap = make_mmap

    def sanitize(c: Call) -> None:
        # a fuzzed zx_process_exit would kill the executor proc
        if c.meta.call_name == "zx_process_exit":
            c.meta = next(s for s in t.syscalls
                          if s.name == "zx_nanosleep")
            c.args = [ConstArg(c.meta.args[0], 0)]

    t.sanitize = sanitize
    if register:
        register_target(t)
    return t


register_lazy_target("fuchsia", "amd64", build_fuchsia_target)
# Zircon syscalls dispatch by vDSO name and auto-number identically on
# every arch; the arm64 target shares the model with its own const
# file (reference ships sys/fuchsia/*_arm64.const the same way).
register_lazy_target("fuchsia", "arm64",
                     lambda: build_fuchsia_target(arch="arm64"))
