"""netbsd/amd64 target: syzlang descriptions + BSD arch hooks.

Third OS target (model-only on this host — there is no NetBSD kernel
to execute against here, exactly like cross-OS models in the
reference tree).  See sys/descriptions/netbsd/*.txt for provenance.
"""

from __future__ import annotations

from syzkaller_tpu.models.target import register_lazy_target
from syzkaller_tpu.sys.bsd import make_bsd_target_builder

build_netbsd_target = make_bsd_target_builder(
    "netbsd",
    string_dictionary=["/dev/null", "./file0", "./file1", "lo0"],
    kill_signals=(9, 17))

register_lazy_target("netbsd", "amd64", build_netbsd_target)
