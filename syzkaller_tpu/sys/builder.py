"""Python builder API for syscall description models.

The builder is the programmatic backend the syzlang compiler lowers
into; it owns type instantiation (per-direction copies of named
structs, as in the reference where StructKey = (name, dir);
reference: prog/types.go:343-351) and drives the layout engine.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field as dc_field
from typing import Callable, Optional, Union

from syzkaller_tpu.compiler.layout import SIZE_UNASSIGNED, LayoutAttrs, LayoutEngine
from syzkaller_tpu.models.prog import Call, ConstArg, PointerArg
from syzkaller_tpu.models.target import Target, register_target
from syzkaller_tpu.models.types import (
    ArrayKind,
    ArrayType,
    BufferKind,
    BufferType,
    ConstType,
    CsumKind,
    CsumType,
    Dir,
    FlagsType,
    IntKind,
    IntType,
    LenType,
    ProcType,
    PtrType,
    ResourceDesc,
    ResourceType,
    StructType,
    Syscall,
    TextKind,
    Type,
    UnionType,
    VmaType,
)

# A TypeSpec is a factory: (builder, dir, field_name, memo) -> Type.
TypeSpec = Callable[["TargetBuilder", Dir, str, dict], Type]


def opt(spec: "TypeSpec") -> "TypeSpec":
    """Mark the produced type optional (syzlang [opt] attribute)."""

    def wrapper(b, d, fname, memo) -> Type:
        t = spec(b, d, fname, memo)
        t.optional = True
        return t

    return wrapper


def int8(**kw) -> TypeSpec:
    return _int_spec(1, **kw)


def int16(**kw) -> TypeSpec:
    return _int_spec(2, **kw)


def int32(**kw) -> TypeSpec:
    return _int_spec(4, **kw)


def int64(**kw) -> TypeSpec:
    return _int_spec(8, **kw)


def intptr(**kw) -> TypeSpec:
    return _int_spec(8, name="intptr", **kw)


def _int_spec(size: int, name: str = "", be: bool = False, bits: int = 0,
              range: Optional[tuple[int, int]] = None,
              fileoff: bool = False) -> TypeSpec:
    def spec(b, d, fname, memo) -> Type:
        kind = IntKind.PLAIN
        rb = re = 0
        if range is not None:
            kind, (rb, re) = IntKind.RANGE, range
        elif fileoff:
            kind = IntKind.FILEOFF
        n = name or f"int{size * 8}{'be' if be else ''}"
        return IntType(name=n, field_name=fname, type_size=size, dir=d,
                       big_endian=be, bitfield_len=bits, kind=kind,
                       range_begin=rb, range_end=re)

    return spec


def const(val: int, size: int = 8, name: str = "", be: bool = False,
          bits: int = 0) -> TypeSpec:
    def spec(b, d, fname, memo) -> Type:
        return ConstType(name=name or f"const{size * 8}", field_name=fname,
                         type_size=size, dir=d, val=val, big_endian=be,
                         bitfield_len=bits)

    return spec


def flags(vals: Union[str, tuple[int, ...]], size: int = 8, be: bool = False,
          bits: int = 0) -> TypeSpec:
    def spec(b, d, fname, memo) -> Type:
        vv = b._flag_sets[vals] if isinstance(vals, str) else tuple(vals)
        return FlagsType(name=vals if isinstance(vals, str) else "flags",
                         field_name=fname, type_size=size, dir=d, vals=vv,
                         big_endian=be, bitfield_len=bits)

    return spec


def len_of(buf: str, size: int = 8, be: bool = False, bits: int = 0) -> TypeSpec:
    return _len_spec(buf, 0, size, be, bits)


def bytesize_of(buf: str, size: int = 8, unit: int = 1, be: bool = False) -> TypeSpec:
    return _len_spec(buf, 8 * unit, size, be, 0)


def bitsize_of(buf: str, size: int = 8, be: bool = False) -> TypeSpec:
    return _len_spec(buf, 1, size, be, 0)


def _len_spec(buf: str, bit_size: int, size: int, be: bool, bits: int) -> TypeSpec:
    def spec(b, d, fname, memo) -> Type:
        return LenType(name=f"len", field_name=fname, type_size=size, dir=d,
                       bit_size=bit_size, buf=buf, big_endian=be,
                       bitfield_len=bits)

    return spec


def proc(start: int, per_proc: int, size: int = 8, opt: bool = False) -> TypeSpec:
    def spec(b, d, fname, memo) -> Type:
        return ProcType(name="proc", field_name=fname, type_size=size, dir=d,
                        optional=opt, values_start=start,
                        values_per_proc=per_proc)

    return spec


def csum(buf: str, kind: CsumKind = CsumKind.INET, protocol: int = 0,
         size: int = 2) -> TypeSpec:
    def spec(b, d, fname, memo) -> Type:
        return CsumType(name="csum", field_name=fname, type_size=size, dir=d,
                        kind=kind, buf=buf, protocol=protocol)

    return spec


def vma(range: Optional[tuple[int, int]] = None, opt: bool = False) -> TypeSpec:
    def spec(b, d, fname, memo) -> Type:
        rb, re = range if range is not None else (0, 0)
        return VmaType(name="vma", field_name=fname, type_size=b.ptr_size,
                       dir=d, optional=opt, range_begin=rb, range_end=re)

    return spec


def ptr(dir_: Dir, elem: Union[str, TypeSpec], opt: bool = False) -> TypeSpec:
    def spec(b, d, fname, memo) -> Type:
        inner = b._instantiate(elem, dir_, "", memo)
        return PtrType(name="ptr", field_name=fname, type_size=b.ptr_size,
                       dir=d, optional=opt, elem=inner)

    return spec


def array(elem: Union[str, TypeSpec],
          count: Optional[tuple[int, int] | int] = None) -> TypeSpec:
    def spec(b, d, fname, memo) -> Type:
        inner = b._instantiate(elem, d, "", memo)
        kind, rb, re = ArrayKind.RAND_LEN, 0, 0
        if count is not None:
            kind = ArrayKind.RANGE_LEN
            rb, re = (count, count) if isinstance(count, int) else count
        if isinstance(inner, IntType) and inner.kind == IntKind.PLAIN \
                and inner.type_size == 1:
            # Special case: a byte array is a buffer — better mutated by
            # the byte-level engine (reference: pkg/compiler/types.go:157-172).
            if kind == ArrayKind.RANGE_LEN:
                fixed = rb == re
                return BufferType(name="array", field_name=fname, dir=d,
                                  kind=BufferKind.BLOB_RANGE,
                                  varlen=not fixed,
                                  type_size=rb if fixed else 0,
                                  range_begin=rb, range_end=re)
            return BufferType(name="array", field_name=fname, dir=d,
                              kind=BufferKind.BLOB_RAND, varlen=True)
        return ArrayType(name="array", field_name=fname,
                         type_size=SIZE_UNASSIGNED, varlen=False, dir=d,
                         elem=inner, kind=kind, range_begin=rb, range_end=re)

    return spec


def buffer(opt: bool = False) -> TypeSpec:
    """Random blob (reference BufferBlobRand)."""

    def spec(b, d, fname, memo) -> Type:
        return BufferType(name="buffer", field_name=fname, varlen=True, dir=d,
                          optional=opt, kind=BufferKind.BLOB_RAND)

    return spec


def blob_range(begin: int, end: int) -> TypeSpec:
    def spec(b, d, fname, memo) -> Type:
        varlen = begin != end
        return BufferType(name="buffer", field_name=fname, varlen=varlen,
                          type_size=0 if varlen else begin, dir=d,
                          kind=BufferKind.BLOB_RANGE, range_begin=begin,
                          range_end=end)

    return spec


def string(values: Union[str, tuple[bytes, ...], None] = None,
           size: int = 0, no_z: bool = False, sub_kind: str = "") -> TypeSpec:
    def spec(b, d, fname, memo) -> Type:
        vv: tuple[bytes, ...] = ()
        sk = sub_kind
        if isinstance(values, str):
            vv = b._string_sets[values]
            sk = values
        elif values is not None:
            vv = tuple(v if isinstance(v, bytes) else v.encode() for v in values)
        if vv and not no_z:
            # Zero-terminate, then pad to the explicit size
            # (reference: pkg/compiler/types.go:492-514).
            vv = tuple(v + b"\x00" * max(1, size - len(v)) for v in vv)
        return BufferType(name="string", field_name=fname, dir=d,
                          varlen=size == 0, type_size=size,
                          kind=BufferKind.STRING, values=vv, no_z=no_z,
                          sub_kind=sk)

    return spec


def filename(size: int = 0, no_z: bool = False) -> TypeSpec:
    def spec(b, d, fname, memo) -> Type:
        return BufferType(name="filename", field_name=fname, dir=d,
                          varlen=size == 0, type_size=size,
                          kind=BufferKind.FILENAME, no_z=no_z)

    return spec


def text(kind: TextKind) -> TypeSpec:
    def spec(b, d, fname, memo) -> Type:
        return BufferType(name="text", field_name=fname, dir=d, varlen=True,
                          kind=BufferKind.TEXT, text=kind)

    return spec


def void() -> TypeSpec:
    """Zero-size type for varlen unions and template padding slots
    (syzlang `void`)."""

    def spec(b, d, fname, memo) -> Type:
        return BufferType(name="void", field_name=fname, dir=d,
                          kind=BufferKind.BLOB_RANGE, varlen=False,
                          type_size=0, range_begin=0, range_end=0)

    return spec


def res(name: str, opt: bool = False) -> TypeSpec:
    """Reference to a named resource."""

    def spec(b, d, fname, memo) -> Type:
        desc = b._resources[name]
        base = desc["base_size"]
        return ResourceType(name=name, field_name=fname, type_size=base,
                            dir=d, optional=opt)

    return spec


@dataclass
class _StructDef:
    name: str
    fields: list[tuple[str, Union[str, TypeSpec]]]
    is_union: bool
    attrs: LayoutAttrs


class TargetBuilder:
    def __init__(self, os: str, arch: str, ptr_size: int = 8,
                 page_size: int = 4096, num_pages: int = 4096,
                 data_offset: int = 0x20000000):
        self.os = os
        self.arch = arch
        self.ptr_size = ptr_size
        self.page_size = page_size
        self.num_pages = num_pages
        self.data_offset = data_offset
        self._structs: dict[str, _StructDef] = {}
        self._resources: dict[str, dict] = {}
        self._flag_sets: dict[str, tuple[int, ...]] = {}
        self._string_sets: dict[str, tuple[bytes, ...]] = {}
        self._syscalls: list[tuple[str, int, list, Optional[str]]] = []
        self._layout_copies: list[tuple[Type, Type]] = []
        self.string_dictionary: list[str] = []
        self.special_types: dict[str, Callable] = {}
        self.make_mmap: Optional[Callable] = None
        self.sanitize_call: Callable[[Call], None] = lambda c: None

    # -- declarations ----------------------------------------------------

    def flag_set(self, name: str, *vals: int) -> None:
        self._flag_sets[name] = tuple(vals)

    def string_set(self, name: str, *vals) -> None:
        self._string_sets[name] = tuple(
            v if isinstance(v, bytes) else v.encode() for v in vals)

    def resource(self, name: str, base_size: int, values: tuple[int, ...] = (0,),
                 parent: Optional[str] = None) -> None:
        kind: tuple[str, ...] = (name,)
        if parent is not None:
            kind = self._resources[parent]["kind"] + (name,)
        self._resources[name] = dict(name=name, base_size=base_size,
                                     values=tuple(values), kind=kind)

    def struct(self, name: str, fields: list[tuple[str, Union[str, TypeSpec]]],
               packed: bool = False, align: int = 0,
               size: Optional[int] = None) -> None:
        self._structs[name] = _StructDef(
            name, fields, False, LayoutAttrs(packed=packed, align=align, size=size))

    def union(self, name: str, fields: list[tuple[str, Union[str, TypeSpec]]],
              varlen: bool = False, size: Optional[int] = None) -> None:
        self._structs[name] = _StructDef(
            name, fields, True, LayoutAttrs(size=size, varlen_attr=varlen))

    def syscall(self, name: str, args: list[tuple[str, Union[str, TypeSpec]]],
                ret: Optional[str] = None, nr: int = 0) -> None:
        self._syscalls.append((name, nr, args, ret))

    # -- instantiation ---------------------------------------------------

    def _instantiate(self, spec: Union[str, TypeSpec], d: Dir, fname: str,
                     memo: dict) -> Type:
        if isinstance(spec, str):
            return self._instantiate_named(spec, d, fname, memo)
        return spec(self, d, fname, memo)

    def _instantiate_named(self, name: str, d: Dir, fname: str, memo: dict) -> Type:
        if name in self._resources:
            return res(name)(self, d, fname, memo)
        sd = self._structs.get(name)
        assert sd is not None, f"unknown type name {name!r}"
        key = (name, int(d))
        cached = memo.get(key)
        if cached is not None:
            # Shared layout per (name, dir); per-use copy carries the
            # field name (as the reference's StructType wrapper does,
            # reference: prog/types.go:305-331).  Layout results are
            # synced onto copies after the layout engine runs.
            t = copy.copy(cached)
            t.field_name = fname
            self._layout_copies.append((cached, t))
            return t
        cls = UnionType if sd.is_union else StructType
        t = cls(name=name, field_name=fname, dir=d, type_size=SIZE_UNASSIGNED)
        memo[key] = t
        t.fields = [self._instantiate(fs, d, fn, memo) for fn, fs in sd.fields]
        return t

    # -- build -----------------------------------------------------------

    def build(self, register: bool = True) -> Target:
        memo: dict = {}
        syscalls: list[Syscall] = []
        for name, nr, args, ret_name in self._syscalls:
            call_name = name.split("$")[0]
            arg_types = [self._instantiate(spec, Dir.IN, fname, memo)
                         for fname, spec in args]
            ret_t: Optional[Type] = None
            if ret_name is not None:
                ret_t = self._instantiate_named(ret_name, Dir.OUT, "ret", memo)
                assert isinstance(ret_t, ResourceType), "ret must be a resource"
            syscalls.append(Syscall(nr=nr, name=name, call_name=call_name,
                                    args=arg_types, ret=ret_t))
        engine = LayoutEngine({sd.name: sd.attrs for sd in self._structs.values()})
        engine.run(syscalls)
        for orig, cp in self._layout_copies:
            cp.type_size = orig.type_size
            cp.varlen = orig.varlen
            cp.fields = orig.fields  # type: ignore[attr-defined]
            if isinstance(orig, StructType):
                cp.align_attr = orig.align_attr  # type: ignore[attr-defined]
        resources = [
            ResourceDesc(name=r["name"], kind=r["kind"], values=r["values"],
                         type=IntType(name=f"int{r['base_size'] * 8}",
                                      type_size=r["base_size"]))
            for r in self._resources.values()
        ]
        target = Target(
            os=self.os, arch=self.arch, ptr_size=self.ptr_size,
            page_size=self.page_size, num_pages=self.num_pages,
            data_offset=self.data_offset, syscalls=syscalls,
            resources=resources,
            string_dictionary=self.string_dictionary,
            special_types=self.special_types,
            sanitize_call=self.sanitize_call,
        )
        if self.make_mmap is not None:
            target.make_mmap = lambda addr, size: self.make_mmap(target, addr, size)
        else:
            target.make_mmap = _default_make_mmap(target)
        target.init()
        if register:
            register_target(target)
        return target


def _default_make_mmap(target: Target):
    """Default mmap-call factory used by targets whose first syscall is
    an mmap(addr vma, len len[addr]) shape."""

    def make(addr: int, size: int) -> Call:
        meta = target.syscalls[0]
        vma_t, len_t = meta.args[0], meta.args[1]
        page_size = target.page_size
        npages = size // page_size
        arg0 = PointerArg.make_vma(vma_t, addr, npages * page_size)
        arg1 = ConstArg(len_t, npages * page_size)
        from syzkaller_tpu.models.prog import make_return_arg

        return Call(meta=meta, args=[arg0, arg1], ret=make_return_arg(meta.ret))

    return make
