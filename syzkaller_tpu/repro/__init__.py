from syzkaller_tpu.repro.repro import (Reproducer, Result, Stats,
                                       bisect_progs, run_from_manager)

__all__ = ["Reproducer", "Result", "Stats", "bisect_progs",
           "run_from_manager"]
