"""Automatic reproducer extraction from crash logs.

Pipeline (reference: pkg/repro/repro.go:60-516): parse the console
log into executed programs → try the last program alone with
escalating durations → else bisect the suffix of programs down to a
minimal crashing set → minimize the program crash-mode → simplify
execution options → render to C and simplify that too.

Testing a candidate is abstracted behind a `tester` callable so the
bisection/minimization logic is hermetic (the reference tests
pkg/repro the same way); production testers execute candidates in a
fresh executor Env (local/sim) or a booted VM instance.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from syzkaller_tpu.csource import Options, write_csource
from syzkaller_tpu.models.minimization import minimize
from syzkaller_tpu.models.encoding import serialize_prog
from syzkaller_tpu.models.parse import parse_log
from syzkaller_tpu.models.prog import Prog
from syzkaller_tpu.utils import log


@dataclass
class Stats:
    """(reference: repro.go:23-41 Stats)"""
    log_entries: int = 0
    extract_prog_time: float = 0.0
    minimize_prog_time: float = 0.0
    simplify_prog_time: float = 0.0
    extract_c_time: float = 0.0
    test_runs: int = 0


@dataclass
class Result:
    """(reference: repro.go:32-41)"""
    prog: Prog
    opts: Options
    prog_text: bytes = b""
    opts_desc: str = ""
    c_src: Optional[bytes] = None
    stats: Stats = field(default_factory=Stats)


# tester(progs, opts, duration_s) -> bool  (did it crash?)
Tester = Callable[[list[Prog], Options, float], bool]


def bisect_progs(progs: list[Prog], pred: Callable[[list[Prog]], bool]
                 ) -> Optional[list[Prog]]:
    """ddmin-style reduction of a crashing program set: repeatedly try
    dropping chunks while the remainder still crashes
    (reference: repro.go:639-700 bisectProgs)."""
    if not pred(progs):
        return None
    n_chunks = 2
    while len(progs) > 1:
        chunk = max(1, len(progs) // n_chunks)
        reduced = False
        i = 0
        while i < len(progs):
            cand = progs[:i] + progs[i + chunk:]
            if cand and pred(cand):
                progs = cand
                reduced = True
            else:
                i += chunk
        if not reduced:
            if chunk == 1:
                break
            n_chunks *= 2
    return progs


class Reproducer:
    def __init__(self, target, tester: Tester,
                 base_duration_s: float = 10.0,
                 extract_c: bool = True):
        self.target = target
        self.tester = tester
        self.base_duration_s = base_duration_s
        self.extract_c = extract_c
        self.stats = Stats()

    def _test(self, progs: list[Prog], opts: Options,
              duration: float) -> bool:
        self.stats.test_runs += 1
        return self.tester(progs, opts, duration)

    def run(self, crash_log: bytes) -> Optional[Result]:
        """(reference: repro.go:60-175 Run + reproduce)"""
        entries = parse_log(self.target, crash_log)
        self.stats.log_entries = len(entries)
        if not entries:
            log.logf(1, "repro: no programs in crash log")
            return None
        opts = Options(repeat=True, procs=1)

        t0 = time.time()
        res = self._extract_prog(entries, opts)
        self.stats.extract_prog_time = time.time() - t0
        if res is None:
            return None
        p, opts = res

        t0 = time.time()
        p = self._minimize(p, opts)
        self.stats.minimize_prog_time = time.time() - t0

        t0 = time.time()
        opts = self._simplify_opts(p, opts)
        self.stats.simplify_prog_time = time.time() - t0

        result = Result(prog=p, opts=opts, prog_text=serialize_prog(p),
                        opts_desc=opts.serialize(), stats=self.stats)
        if self.extract_c:
            t0 = time.time()
            result.c_src = write_csource(p, opts)
            self.stats.extract_c_time = time.time() - t0
        return result

    # -- stages -----------------------------------------------------------

    def _extract_prog(self, entries, opts: Options
                      ) -> Optional[tuple[Prog, Options]]:
        """Last-single-prog with escalating durations, then multi-prog
        bisection over the log suffix (reference: repro.go:233-420)."""
        # Single-program attempts: the last few entries overall plus
        # the final entry of EACH proc — on an interleaved multi-proc
        # console the crasher is the last program of its own proc, not
        # necessarily one of the last lines (reference: repro.go
        # indexes candidate entries per procs count).
        last_per_proc: dict[int, object] = {}
        for e in entries:
            last_per_proc[e.proc] = e
        singles = list(reversed(entries[-5:]))
        for e in last_per_proc.values():
            if e not in singles:
                singles.append(e)
        for duration_mult in (1, 3):
            duration = self.base_duration_s * duration_mult
            for entry in singles:
                if self._test([entry.p], opts, duration):
                    log.logf(1, "repro: single-program reproducer found")
                    return entry.p, opts
        # Multi-program: bisect the suffix (state built up by earlier
        # programs may be needed).
        suffix = [e.p for e in entries[-20:]]
        subset = bisect_progs(
            suffix, lambda ps: self._test(ps, opts,
                                          self.base_duration_s * 3))
        if subset:
            # Concatenate the surviving programs into one.
            combined = subset[0].clone()
            for extra in subset[1:]:
                c = extra.clone()
                combined.calls.extend(c.calls)
            if self._test([combined], opts, self.base_duration_s * 3):
                return combined, opts
            # fall back to the first surviving program alone
            if len(subset) == 1:
                return subset[0], opts
        return None

    def _minimize(self, p: Prog, opts: Options) -> Prog:
        """Crash-mode minimization: every step re-validated by
        execution (reference: repro.go:423-446 → prog.Minimize)."""
        def pred(cand: Prog, _call_index: int) -> bool:
            return self._test([cand], opts, self.base_duration_s)

        p2, _ = minimize(p, -1, crash=True, pred0=pred)
        return p2

    def _simplify_opts(self, p: Prog, opts: Options) -> Options:
        """Drop execution options one at a time while it still crashes
        (reference: repro.go:448-478 simplifyProg)."""
        simplifications = [
            ("repeat", False),
            ("procs", 1),
            ("sandbox", "none"),
            ("threaded", False),
            ("collide", False),
        ]
        for attr, plain in simplifications:
            if getattr(opts, attr) == plain:
                continue
            trial = Options(**{**opts.__dict__, attr: plain})
            if self._test([p], trial, self.base_duration_s):
                opts = trial
        return opts


# -- production testers ---------------------------------------------------


def make_env_tester(target, title_filter: Optional[str] = None,
                    runs_per_test: int = 3) -> Tester:
    """Executes candidates against a fresh local executor (sim kernel)
    and reports whether any run crashed (with a matching title when
    title_filter is set).  The local/sim analogue of booting a VM per
    test (reference: repro.go:518-626 testProgs)."""
    from syzkaller_tpu.ipc.env import (ExecOpts, ExecutorCrash,
                                       ExecutorFailure, make_env)
    from syzkaller_tpu.models.encodingexec import serialize_for_exec
    from syzkaller_tpu.report import get_reporter

    reporter = get_reporter(target.os)

    def tester(progs: list[Prog], opts: Options, duration: float) -> bool:
        env = make_env(0)
        try:
            deadline = time.monotonic() + min(duration, 30.0)
            runs = 0
            while time.monotonic() < deadline and runs < runs_per_test:
                runs += 1
                for p in progs:
                    try:
                        env.exec(ExecOpts(), serialize_for_exec(p))
                    except ExecutorCrash as e:
                        if title_filter is None:
                            return True
                        rep = reporter.parse(e.log.encode())
                        if rep is not None and rep.title == title_filter:
                            return True
                        return False  # crashed differently
                    except ExecutorFailure:
                        pass
                if not opts.repeat:
                    break
            return False
        finally:
            env.close()

    return tester


def run_from_manager(mgr, title: str, crash_log: bytes
                     ) -> Optional[Result]:
    """Entry point used by the manager's repro scheduler."""
    from syzkaller_tpu.report import get_reporter

    # On a real kernel the VM dies at the oops, so the log ends near
    # the crasher.  The sim executor is respawned by the fuzzer, which
    # keeps logging programs until the monitor kills the instance —
    # cut the log at the first oops so "last entries" means "last
    # before the crash", not detection-latency noise.
    try:
        rep = get_reporter(mgr.target.os).parse(crash_log)
        if rep is not None and rep.start_pos > 0:
            crash_log = crash_log[:rep.start_pos]
    except Exception:
        pass
    tester = make_env_tester(mgr.target, title_filter=title)
    r = Reproducer(mgr.target, tester)
    return r.run(crash_log)
