"""Control-plane wire types.

Dataclass mirrors of the reference protocol structs
(pkg/rpctype/rpctype.go:12-114): manager⇄fuzzer
(Connect/Check/Poll/NewInput) and manager⇄hub (HubConnect/HubSync).
All types round-trip through plain dicts for the JSON transport.
"""

from __future__ import annotations

from dataclasses import dataclass, field, asdict
from typing import Optional


@dataclass
class RPCInput:
    """A triaged corpus input (reference: rpctype.go:12-18)."""
    call: str = ""
    prog: str = ""
    signal: tuple[list[int], list[int]] = field(default_factory=lambda: ([], []))
    cover: list[int] = field(default_factory=list)

    def to_dict(self) -> dict:
        return asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "RPCInput":
        sig = d.get("signal") or ([], [])
        return RPCInput(call=d.get("call", ""), prog=d.get("prog", ""),
                        signal=(list(sig[0]), list(sig[1])),
                        cover=list(d.get("cover") or []))


@dataclass
class RPCCandidate:
    """A corpus program pending fuzzer-side triage
    (reference: rpctype.go:20-24)."""
    prog: str = ""
    minimized: bool = False
    smashed: bool = False

    def to_dict(self) -> dict:
        return asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "RPCCandidate":
        return RPCCandidate(prog=d.get("prog", ""),
                            minimized=bool(d.get("minimized")),
                            smashed=bool(d.get("smashed")))


@dataclass
class ConnectArgs:
    """(reference: rpctype.go:26-28)"""
    name: str = ""

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass
class ConnectRes:
    """Everything a fresh fuzzer needs (reference: rpctype.go:30-40).

    `epoch`/`lease_s` are the session pair minted per Connect
    (docs/health.md): the epoch namespaces the idempotency seqs and
    detects manager restarts; the lease is how long the manager keeps
    this fuzzer's queues alive without a poll."""
    prios: list[list[float]] = field(default_factory=list)
    corpus: list[dict] = field(default_factory=list)  # RPCInput dicts
    max_signal: tuple[list[int], list[int]] = \
        field(default_factory=lambda: ([], []))
    candidates: list[dict] = field(default_factory=list)
    enabled_calls: list[int] = field(default_factory=list)
    need_check: bool = True
    epoch: str = ""
    lease_s: float = 0.0

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass
class CheckArgs:
    """Fuzzer capability report (reference: rpctype.go:42-50)."""
    name: str = ""
    kcov: bool = False
    leak: bool = False
    fault: bool = False
    comps: bool = False
    calls: list[int] = field(default_factory=list)
    disabled: list[tuple[str, str]] = field(default_factory=list)

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass
class NewInputArgs:
    """(reference: rpctype.go:52-55).  `epoch`/`seq`/`ack_seq` are the
    idempotency-session tags (zero/empty on the legacy unsessioned
    path)."""
    name: str = ""
    call_index: int = 0
    input: dict = field(default_factory=dict)  # RPCInput dict
    epoch: str = ""
    seq: int = 0
    ack_seq: int = 0

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass
class PollArgs:
    """(reference: rpctype.go:57-62).  The session tags plus
    `device_state` — the fuzzer's worst pipeline/triage breaker state
    ("closed"/"half_open"/"open"), the admission controller's input."""
    name: str = ""
    need_candidates: bool = False
    stats: dict[str, int] = field(default_factory=dict)
    max_signal: tuple[list[int], list[int]] = \
        field(default_factory=lambda: ([], []))
    epoch: str = ""
    seq: int = 0
    ack_seq: int = 0
    device_state: str = "closed"

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass
class ThrottleHint:
    """Admission-control verdict riding every Poll reply: the fleet's
    aggregated breaker state, the shrunk per-poll candidate allotment,
    and the factor to stretch the poll cadence by while degraded."""
    state: str = "closed"
    max_candidates: int = 100
    poll_interval_mult: float = 1.0

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass
class PollRes:
    """(reference: rpctype.go:64-69)"""
    candidates: list[dict] = field(default_factory=list)
    new_inputs: list[dict] = field(default_factory=list)
    max_signal: tuple[list[int], list[int]] = \
        field(default_factory=lambda: ([], []))
    throttle: dict = field(default_factory=dict)  # ThrottleHint dict

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass
class HubConnectArgs:
    """(reference: rpctype.go:75-88)"""
    client: str = ""
    key: str = ""
    manager: str = ""
    fresh: bool = False
    calls: list[str] = field(default_factory=list)
    corpus: list[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass
class HubSyncArgs:
    """(reference: rpctype.go:90-105)"""
    client: str = ""
    key: str = ""
    manager: str = ""
    need_repros: bool = False
    repros: list[str] = field(default_factory=list)
    add: list[str] = field(default_factory=list)
    delete: list[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass
class HubSyncRes:
    """(reference: rpctype.go:107-114)"""
    progs: list[str] = field(default_factory=list)
    repros: list[str] = field(default_factory=list)
    more: int = 0

    def to_dict(self) -> dict:
        return asdict(self)
