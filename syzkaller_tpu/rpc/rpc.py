"""TCP RPC transport: length-prefixed JSON frames with zlib for large
payloads, TCP keep-alive, threaded server.

The control-plane protocols (manager⇄fuzzer, manager⇄hub) ride this —
the equivalent of the reference's net/rpc + gob transport with its
keep-alive tuning (reference: pkg/rpctype/rpc.go:20-86).  Method
dispatch is by "Service.Method" name to a registered receiver whose
python method `Method` takes one dict argument and returns a dict —
mirroring net/rpc's (args, reply) convention.  Big-payload exchanges
(corpus downloads) use short-lived connections created per call to
avoid buffer bloat on the long-lived poll connection (reference:
syz-fuzzer/fuzzer.go:231-238, syz-manager/manager.go:1115-1124).
"""

from __future__ import annotations

import json
import random
import socket
import struct
import threading
import time
import zlib
from typing import Any, Callable, Optional

from syzkaller_tpu import telemetry
from syzkaller_tpu.health.envsafe import env_float, env_int
from syzkaller_tpu.health.faultinject import FaultInjected, fault_point
from syzkaller_tpu.telemetry import lineage
from syzkaller_tpu.utils import log

_FRAME = struct.Struct("<IB")  # payload length, flags
_FLAG_ZLIB = 1
#: The frame carries a lineage trace context (telemetry/lineage.py):
#: lineage.WIRE bytes follow the header before the payload.  This is
#: how a sampled mutant's trace id crosses the process boundary —
#: the receive side records the `rpc.frame` hop and parks the context
#: in a thread-local for the dispatched method (Manager.NewInput).
_FLAG_TRACE = 2
#: The frame carries a binary annex: an 8-byte length follows the
#: header (after any trace context), and that many raw bytes follow
#: the JSON payload.  This is the zero-copy result-distribution path
#: (ISSUE 12): the serving plane ships assembled mutants as arena
#: memoryviews written straight to the socket — the JSON reply holds
#: only (offset, length) refs into the annex, and no per-mutant copy
#: happens on either side of the compress/JSON machinery.
_FLAG_ANNEX = 4
_ANNEX = struct.Struct("<Q")
_COMPRESS_MIN = 4 << 10
_MAX_FRAME = 512 << 20

# Transport telemetry (docs/observability.md): frame/byte counts plus
# span-timed frame latencies (rpc.send / rpc.recv) — recv latency is
# the poll-loop wait, so its percentiles expose a slow or silent peer.
_M_FRAMES_SENT = telemetry.counter(
    "tz_rpc_frames_sent_total", "RPC frames sent")
_M_FRAMES_RECV = telemetry.counter(
    "tz_rpc_frames_recv_total", "RPC frames received")
_M_BYTES_SENT = telemetry.counter(
    "tz_rpc_bytes_sent_total", "RPC wire bytes sent (incl. headers)")
_M_BYTES_RECV = telemetry.counter(
    "tz_rpc_bytes_recv_total", "RPC wire bytes received (incl. headers)")
# Peer-churn accounting (docs/observability.md): every server-side
# connection ends in exactly one of dropped (peer closed between
# frames — normal fuzzer-VM death/restart) or errored (mid-frame
# failure, oversized/garbled frame, injected fault).
_M_CONN_ACCEPTED = telemetry.counter(
    "tz_rpc_conn_accepted_total", "RPC connections accepted")
_M_CONN_DROPPED = telemetry.counter(
    "tz_rpc_conn_dropped_total",
    "RPC connections closed by the peer at a frame boundary")
_M_CONN_ERRORS = telemetry.counter(
    "tz_rpc_conn_errors_total",
    "RPC connections torn down mid-frame or on a protocol error")
# Session-retry accounting (client side): resends after a completed
# send (safe only because the server's reply cache dedups by seq),
# the cumulative backoff wait, and full re-Connect resyncs driven by
# ReconnectRequired.
_M_RETRIES = telemetry.counter(
    "tz_rpc_retries_total", "session RPC resend attempts")
_M_RETRY_WAIT = telemetry.counter(
    "tz_rpc_retry_wait_seconds_total",
    "time spent in session-retry backoff")
_M_RECONNECTS = telemetry.counter(
    "tz_rpc_reconnects_total",
    "full session resyncs after ReconnectRequired")


class RPCError(Exception):
    pass


class ReconnectRequired(RPCError):
    """Structured server verdict: the caller's session epoch is stale
    (manager restarted) or its lease was reaped — only a full
    re-Connect resync can make further mutating calls safe.  Carried
    on the wire as error_kind="reconnect_required" so the client
    raises this type instead of a generic RPCError."""


class _PeerClosed(ConnectionError):
    """EOF at an exact frame boundary: the peer hung up cleanly
    between requests, as a dying fuzzer VM does — distinct from a
    mid-frame failure so the server books it as a drop, not an
    error."""


def _send_frame(sock: socket.socket, obj: Any, trace=None,
                annex=None) -> None:
    # Fault seam: a scripted `fail` here raises FaultInjected (a
    # ConnectionError), driving the client's reconnect/retry path and
    # the server's connection-drop path exactly as a real peer death
    # would (health/faultinject.py).
    fault_point("rpc.send_frame")
    with telemetry.span("rpc.send"):
        data = json.dumps(obj, separators=(",", ":")).encode()
        flags = 0
        if len(data) >= _COMPRESS_MIN:
            data = zlib.compress(data, 1)
            flags |= _FLAG_ZLIB
        header = b""
        if trace is not None and trace.sampled:
            flags |= _FLAG_TRACE
            header = lineage.to_wire(trace)
        # `annex`: one bytes-like or a sequence of them.  The parts
        # are sent as-is, one sendall each — memoryviews into batch
        # arenas go straight to the socket, never joined or copied.
        parts = []
        annex_len = 0
        if annex is not None:
            parts = [annex] if isinstance(annex, (bytes, bytearray,
                                                  memoryview)) \
                else list(annex)
            annex_len = sum(len(p) for p in parts)
            flags |= _FLAG_ANNEX
            header += _ANNEX.pack(annex_len)
        sock.sendall(_FRAME.pack(len(data), flags) + header + data)
        for part in parts:
            sock.sendall(part)
    _M_FRAMES_SENT.inc()
    _M_BYTES_SENT.inc(_FRAME.size + len(header) + len(data) + annex_len)


def _recv_exact(sock: socket.socket, n: int,
                at_boundary: bool = False) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if at_boundary and not buf:
                raise _PeerClosed("connection closed")
            raise ConnectionError("connection closed")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket, want_annex: bool = False) -> Any:
    fault_point("rpc.recv_frame")
    trace_bytes = 0
    annex = None
    annex_len = 0
    with telemetry.span("rpc.recv"):
        hdr = _recv_exact(sock, _FRAME.size, at_boundary=True)
        length, flags = _FRAME.unpack(hdr)
        if length > _MAX_FRAME:
            raise RPCError(f"oversized frame ({length} bytes)")
        ctx = None
        if flags & _FLAG_TRACE:
            trace_bytes = lineage.WIRE.size
            ctx = lineage.from_wire(_recv_exact(sock, trace_bytes))
        if flags & _FLAG_ANNEX:
            annex_len, = _ANNEX.unpack(
                _recv_exact(sock, _ANNEX.size))
            if annex_len > _MAX_FRAME:
                raise RPCError(f"oversized annex ({annex_len} bytes)")
        data = _recv_exact(sock, length)
        # The annex is drained even when the caller did not ask for
        # it — it belongs to this frame and must not bleed into the
        # next one's header.  Drained BEFORE the payload is decoded:
        # a zlib/json failure below leaves this socket at an exact
        # frame boundary, so a pooled client connection (RPCClient
        # only tears the socket down on ConnectionError/OSError) can
        # carry the next call instead of reading annex bytes as a
        # frame header (the ROADMAP's drain-on-error annex caveat).
        if annex_len:
            annex = _recv_exact(sock, annex_len)
        if flags & _FLAG_ZLIB:
            data = zlib.decompress(data)
    # Park the decoded context (None clears a stale one) so the
    # dispatched method on this thread can continue the chain.
    lineage.set_current(ctx)
    _M_FRAMES_RECV.inc()
    _M_BYTES_RECV.inc(_FRAME.size + trace_bytes + length + annex_len
                      + (_ANNEX.size if flags & _FLAG_ANNEX else 0))
    obj = json.loads(data)
    return (obj, annex) if want_annex else obj


def _setup_keepalive(sock: socket.socket) -> None:
    # Aggressive keep-alive so dead VMs are detected in ~1 min
    # (reference: pkg/rpctype/rpc.go setupKeepAlive, 1 min period).
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_KEEPIDLE, 60)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_KEEPINTVL, 60)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_KEEPCNT, 3)
    except OSError:
        pass


class RPCServer:
    """Threaded RPC server dispatching "Service.Method" to receivers
    (reference: pkg/rpctype/rpc.go:20-50 NewRPCServer/Serve)."""

    def __init__(self, addr: tuple[str, int] = ("127.0.0.1", 0)):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(addr)
        self._sock.listen(64)
        self.addr = self._sock.getsockname()
        self._services: dict[str, object] = {}
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()

    def register(self, name: str, receiver: object) -> None:
        self._services[name] = receiver

    def serve_in_background(self) -> None:
        self._thread = threading.Thread(target=self.serve, daemon=True)
        self._thread.start()

    def serve(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            with self._conns_lock:
                if self._stopped.is_set():
                    conn.close()
                    return
                self._conns.add(conn)
            threading.Thread(target=self._handle_conn, args=(conn,),
                             daemon=True).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        _setup_keepalive(conn)
        _M_CONN_ACCEPTED.inc()
        try:
            with conn:
                while True:
                    req = _recv_frame(conn)
                    resp = self._dispatch(req)
                    annex = resp.pop("_annex", None)
                    _send_frame(conn, resp, annex=annex)
        except _PeerClosed:
            # Clean hangup between frames: normal peer churn (a
            # transient call finishing, a fuzzer VM restarting) —
            # counted but not timeline-worthy.
            _M_CONN_DROPPED.inc()
        except (ConnectionError, OSError, json.JSONDecodeError,
                zlib.error) as e:
            _M_CONN_ERRORS.inc()
            telemetry.record_event(
                "rpc.conn_drop", f"{type(e).__name__}: {e}")
        finally:
            with self._conns_lock:
                self._conns.discard(conn)

    def _dispatch(self, req: dict) -> dict:
        rid = req.get("id")
        method = req.get("method", "")
        try:
            service, _, fn_name = method.partition(".")
            recv = self._services.get(service)
            fn: Optional[Callable] = getattr(recv, fn_name, None) \
                if recv is not None and not fn_name.startswith("_") else None
            if fn is None:
                raise RPCError(f"unknown method {method!r}")
            result = fn(req.get("params") or {})
            # A handler returning (dict, annex) ships the annex as
            # the reply frame's zero-copy binary tail; "_annex" is an
            # out-of-band key the connection loop pops before the
            # JSON encode ever sees it.
            if isinstance(result, tuple) and len(result) == 2:
                result, annex = result
                return {"id": rid, "result": result, "_annex": annex}
            return {"id": rid, "result": result}
        except FaultInjected:
            # A scripted seam fault inside a handler models the server
            # dying mid-call: propagate so the connection is torn down
            # and the client sees a real ConnectionError (its retry
            # path, not a tidy error reply, is what's under test).
            raise
        except ReconnectRequired as e:
            return {"id": rid, "error": f"{type(e).__name__}: {e}",
                    "error_kind": "reconnect_required"}
        except Exception as e:  # delivered to the caller, server lives on
            return {"id": rid, "error": f"{type(e).__name__}: {e}"}

    def close(self) -> None:
        """Full shutdown: the listener AND every accepted connection —
        a restarting manager must be able to rebind its port at once,
        not wait for stragglers' sockets to drain.  shutdown() (not
        just close()) on the listener is what unblocks a thread parked
        in accept(); a blocked accept otherwise keeps the kernel
        socket alive past close() and the port stays taken."""
        self._stopped.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass


class RPCClient:
    """Blocking single-connection client (reference: rpc.go:52-86).

    One outstanding call at a time per connection, matching the
    fuzzer's serialized poll loop; `name` tags the caller identity
    carried inside request params by convention.
    """

    def __init__(self, addr: tuple[str, int], name: str = "",
                 timeout_s: float = 60.0,
                 retries: Optional[int] = None,
                 backoff_s: Optional[float] = None):
        self.addr = tuple(addr)
        self.name = name
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._next_id = 0
        # Session state (docs/health.md "control-plane sessions"):
        # minted by Manager.Connect, carried on every mutating call so
        # the server's reply cache makes post-send retries safe.
        self.retries = env_int("TZ_RPC_RETRIES", 3) \
            if retries is None else retries
        self.backoff_s = env_float("TZ_RPC_BACKOFF_S", 0.2) \
            if backoff_s is None else backoff_s
        self.epoch: Optional[str] = None
        self.on_reconnect: Optional[Callable[[], None]] = None
        self._seq = 0
        self._acked = 0
        self._seq_lock = threading.Lock()

    def set_session(self, epoch: str,
                    on_reconnect: Optional[Callable[[], None]] = None
                    ) -> None:
        """Arm (or re-arm, after a resync) the idempotent-call session:
        `epoch` comes from the Connect reply; `on_reconnect`, when
        set, is invoked on a ReconnectRequired verdict and must
        re-Connect + resync before the call is re-issued."""
        self.epoch = epoch
        if on_reconnect is not None:
            self.on_reconnect = on_reconnect

    def _next_seq(self) -> int:
        with self._seq_lock:
            self._seq += 1
            return self._seq

    def _mark_acked(self, seq: int) -> None:
        with self._seq_lock:
            if seq > self._acked:
                self._acked = seq

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(self.addr, timeout=self.timeout_s)
        _setup_keepalive(sock)
        return sock

    def call(self, method: str, params: Optional[dict] = None,
             trace=None, want_annex: bool = False) -> Any:
        """`trace` (a lineage.TraceContext) rides the request frame's
        header so the server side can correlate this call into the
        mutant's lifecycle track (telemetry/lineage.py).  With
        `want_annex` the return value is (result, annex_bytes) —
        annex_bytes is None when the reply carried no binary tail."""
        with self._lock:
            self._next_id += 1
            req = {"id": self._next_id, "method": method,
                   "params": params or {}}
            for attempt in range(2):
                reused = self._sock is not None
                if not reused:
                    self._sock = self._connect()
                try:
                    _send_frame(self._sock, req, trace=trace)
                except (ConnectionError, OSError):
                    # Send on a stale pooled connection may fail without
                    # the server having executed anything — reconnect and
                    # re-send once.  Failures after the send completed
                    # must NOT retry (the RPC may have run server-side:
                    # duplicating a Poll/NewInput corrupts state).
                    self.close()
                    if not reused or attempt == 1:
                        raise
                    continue
                try:
                    resp, annex = _recv_frame(self._sock,
                                              want_annex=True)
                except (ConnectionError, OSError):
                    self.close()
                    raise
                break
            if resp.get("error"):
                if resp.get("error_kind") == "reconnect_required":
                    raise ReconnectRequired(resp["error"])
                raise RPCError(resp["error"])
            result = resp.get("result")
            return (result, annex) if want_annex else result

    def call_session(self, method: str, params: Optional[dict] = None,
                     trace=None, want_annex: bool = False) -> Any:
        """A mutating call under the idempotency session: tags the
        params with (name, epoch, seq, ack_seq) and retries with
        exponential backoff + jitter across connection failures —
        including after a completed send, which plain call() must
        never do.  The server's per-fuzzer reply cache replays the
        seq's reply if the first attempt did run, so at-most-once
        mutation holds across every retry.  A ReconnectRequired
        verdict (manager restart / reaped lease) runs the installed
        on_reconnect resync and re-issues under the fresh epoch.

        Without a session (epoch unset — standalone tools, tests
        driving the legacy protocol) this degrades to plain call()."""
        params = dict(params or {})
        params.setdefault("name", self.name)
        if self.epoch is None:
            return self.call(method, params, trace=trace,
                             want_annex=want_annex)
        seq = self._next_seq()
        params["seq"] = seq
        attempts = max(1, self.retries + 1)
        delay = max(0.001, self.backoff_s)
        reconnects = 0
        for attempt in range(attempts):
            params["epoch"] = self.epoch
            with self._seq_lock:
                params["ack_seq"] = self._acked
            try:
                result = self.call(method, params, trace=trace,
                                   want_annex=want_annex)
            except ReconnectRequired:
                # Stale epoch or reaped lease: only a full resync can
                # recover.  Bounded separately from connection retries
                # so a crash-looping manager can't spin us forever.
                if self.on_reconnect is None or reconnects >= 2:
                    raise
                reconnects += 1
                _M_RECONNECTS.inc()
                telemetry.record_event(
                    "rpc.reconnect", f"{method} seq={seq}")
                self.on_reconnect()  # re-Connect; updates self.epoch
                continue
            except (ConnectionError, OSError) as e:
                if attempt == attempts - 1:
                    raise
                _M_RETRIES.inc()
                wait = delay * (1.0 + random.random())
                delay = min(delay * 2, 5.0)
                log.logf(2, "rpc %s seq=%d failed (%s); retry in %.2fs",
                         method, seq, e, wait)
                _M_RETRY_WAIT.inc(wait)
                time.sleep(wait)
                continue
            self._mark_acked(seq)
            return result

    def call_transient(self, method: str,
                       params: Optional[dict] = None) -> Any:
        """One-shot connection for big payloads (fuzzer.go:231-238)."""
        sock = self._connect()
        try:
            _send_frame(sock, {"id": 0, "method": method,
                               "params": params or {}})
            resp = _recv_frame(sock)
        finally:
            sock.close()
        if resp.get("error"):
            raise RPCError(resp["error"])
        return resp.get("result")

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
