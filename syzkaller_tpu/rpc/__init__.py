from syzkaller_tpu.rpc.replycache import ReplyCache
from syzkaller_tpu.rpc.rpc import (ReconnectRequired, RPCClient,
                                   RPCError, RPCServer)

__all__ = ["RPCClient", "RPCServer", "RPCError", "ReconnectRequired",
           "ReplyCache"]
