from syzkaller_tpu.rpc.rpc import RPCClient, RPCServer, RPCError

__all__ = ["RPCClient", "RPCServer", "RPCError"]
