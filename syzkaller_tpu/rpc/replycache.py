"""Byte-and-entry-bounded reply cache for idempotent RPC sessions.

PR 8 gave every session peer (manager fuzzers, serve tenants, hub
managers) a per-name reply cache so a retried `(epoch, seq)` replays
instead of double-applying.  The original bound was entry-count only —
fine for the manager's small JSON replies, but the serving plane and
the hub cache `(reply, annex)` tuples whose annex tails are arena
slices: 128 entries of multi-MB annexes pin hundreds of MB of arena
memory alive long after the tenant acked them (the ROADMAP's first
`_FLAG_ANNEX` caveat).  This cache bounds both dimensions:

  * TZ_RPC_REPLY_CACHE     — max entries (default 128), as before,
  * TZ_RPC_REPLY_CACHE_MB  — max approximate bytes across cached
    replies + annexes (default 64 MB),

evicting oldest-seq first.  The newest entry is NEVER evicted even if
it alone exceeds the byte cap: dropping the reply that the in-flight
retry may be about to ask for would break at-most-once and re-apply
the mutation — a correctness bug traded for a transient memory spike.

Sizes are estimates (exact for bytes-likes, JSON-shaped guess for the
reply dict) — the bound exists to stop arena pinning, not to account
bytes to the byte.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from syzkaller_tpu import telemetry
from syzkaller_tpu.health.envsafe import env_float, env_int

_M_EVICTED_BYTES = telemetry.counter(
    "tz_rpc_reply_cache_evicted_bytes_total",
    "approximate bytes freed by reply-cache eviction (entry or byte "
    "bound) — annex payloads pinned by cached replies are released "
    "here")


def approx_size(obj: Any) -> int:
    """Cheap recursive wire-size estimate of a cached reply: exact for
    bytes-likes (the annex tails this bound exists for), JSON-shaped
    for containers/scalars.  Never raises on odd types — an unknown
    object just costs a flat guess."""
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return len(obj)
    if isinstance(obj, str):
        return len(obj) + 2
    if obj is None or isinstance(obj, bool):
        return 4
    if isinstance(obj, (int, float)):
        return 8
    if isinstance(obj, dict):
        return 2 + sum(approx_size(k) + approx_size(v) + 2
                       for k, v in obj.items())
    if isinstance(obj, (list, tuple)):
        return 2 + sum(approx_size(v) + 1 for v in obj)
    return 16


class ReplyCache:
    """seq -> cached reply (any JSON-able value, or a (reply, annex)
    tuple on annex-carrying services), bounded by entries AND bytes."""

    def __init__(self, entries: Optional[int] = None,
                 max_mb: Optional[float] = None):
        self.max_entries = max(1, env_int("TZ_RPC_REPLY_CACHE", 128)
                               if entries is None else int(entries))
        mb = env_float("TZ_RPC_REPLY_CACHE_MB", 64.0) \
            if max_mb is None else float(max_mb)
        self.max_bytes = max(1, int(mb * (1 << 20)))
        self._lock = threading.Lock()
        self._items: dict[int, tuple[Any, int]] = {}
        self.bytes = 0
        self.evicted_bytes = 0

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, seq: int) -> bool:
        with self._lock:
            return seq in self._items

    def __iter__(self):
        with self._lock:
            return iter(sorted(self._items))

    def __getitem__(self, seq: int) -> Any:
        with self._lock:
            return self._items[seq][0]

    def __eq__(self, other: Any) -> bool:
        """Equality against a plain {seq: reply} dict — the shape the
        session planes used before the byte bound existed; keeps the
        dict-era assertions meaningful."""
        if isinstance(other, dict):
            with self._lock:
                return {k: v[0] for k, v in self._items.items()} == other
        return NotImplemented

    __hash__ = None  # mutable container

    def get(self, seq: int) -> Any:
        """The cached reply for seq, or None (replies are dicts/tuples
        by protocol, never None, so the sentinel is unambiguous)."""
        with self._lock:
            item = self._items.get(seq)
            return item[0] if item is not None else None

    def put(self, seq: int, value: Any) -> None:
        size = approx_size(value)
        with self._lock:
            old = self._items.pop(seq, None)
            if old is not None:
                self.bytes -= old[1]
            self._items[seq] = (value, size)
            self.bytes += size
            while len(self._items) > 1 and (
                    len(self._items) > self.max_entries
                    or self.bytes > self.max_bytes):
                oldest = min(self._items)
                if oldest == seq:
                    break  # never evict the just-cached reply
                _val, osize = self._items.pop(oldest)
                self.bytes -= osize
                self.evicted_bytes += osize
                _M_EVICTED_BYTES.inc(osize)

    def snapshot(self) -> dict:
        with self._lock:
            return {"entries": len(self._items), "bytes": self.bytes,
                    "evicted_bytes": self.evicted_bytes}
