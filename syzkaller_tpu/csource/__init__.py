from syzkaller_tpu.csource.csource import Options, write_csource
from syzkaller_tpu.csource.build import build_csource

__all__ = ["Options", "write_csource", "build_csource"]
