"""Compile generated C reproducers (reference: pkg/csource/build.go)."""

from __future__ import annotations

import os
import subprocess
import tempfile
from typing import Optional


class BuildError(Exception):
    pass


def build_csource(src: bytes, out_path: Optional[str] = None,
                  cc: str = "gcc", extra_flags: Optional[list[str]] = None,
                  compile_only: bool = False) -> str:
    """Compile to a binary; returns its path (caller owns the file).

    compile_only (-c) supports cross-width gates on hosts without the
    target libc: a linux/386 reproducer compile-checks with
    `extra_flags=m32_flags(shim_dir)` even though no 32-bit libc.a
    exists to link (the run path needs a real 32-bit userland)."""
    fd, src_path = tempfile.mkstemp(suffix=".c", prefix="tz-repro-")
    with os.fdopen(fd, "wb") as f:
        f.write(src)
    if out_path is None:
        fd2, out_path = tempfile.mkstemp(prefix="tz-repro-bin-")
        os.close(fd2)
    mode = ["-c"] if compile_only else ["-static-pie", "-pthread"]
    args = [cc, "-o", out_path, src_path, "-O1", *mode,
            *(extra_flags or [])]
    res = subprocess.run(args, capture_output=True)
    if res.returncode != 0 and not compile_only:
        # -static-pie unsupported on some toolchains: retry dynamic
        args = [cc, "-o", out_path, src_path, "-O1", "-pthread",
                *(extra_flags or [])]
        res = subprocess.run(args, capture_output=True)
    os.unlink(src_path)
    if res.returncode != 0:
        raise BuildError(f"failed to build reproducer:\n"
                         f"{res.stderr.decode()[-2048:]}")
    return out_path


def m32_flags(shim_dir: str) -> list[str]:
    """cflags to compile-check a 32-bit reproducer on a 64-bit host
    with no 32-bit libc-dev (utils/m32 holds the shared shim logic;
    shim_dir is required so the caller owns its lifetime)."""
    from syzkaller_tpu.utils.m32 import m32_compile_flags

    return m32_compile_flags(shim_dir)
