"""Compile generated C reproducers (reference: pkg/csource/build.go)."""

from __future__ import annotations

import os
import subprocess
import tempfile
from typing import Optional


class BuildError(Exception):
    pass


def build_csource(src: bytes, out_path: Optional[str] = None,
                  cc: str = "gcc", extra_flags: Optional[list[str]] = None
                  ) -> str:
    """Compile to a binary; returns its path (caller owns the file)."""
    fd, src_path = tempfile.mkstemp(suffix=".c", prefix="tz-repro-")
    with os.fdopen(fd, "wb") as f:
        f.write(src)
    if out_path is None:
        fd2, out_path = tempfile.mkstemp(prefix="tz-repro-bin-")
        os.close(fd2)
    args = [cc, "-o", out_path, src_path, "-O1", "-static-pie", "-pthread",
            *(extra_flags or [])]
    res = subprocess.run(args, capture_output=True)
    if res.returncode != 0:
        # -static-pie unsupported on some toolchains: retry dynamic
        args = [cc, "-o", out_path, src_path, "-O1", "-pthread",
                *(extra_flags or [])]
        res = subprocess.run(args, capture_output=True)
    os.unlink(src_path)
    if res.returncode != 0:
        raise BuildError(f"failed to build reproducer:\n"
                         f"{res.stderr.decode()[-2048:]}")
    return out_path
