"""Standalone C reproducer generation.

Renders a typed Prog into a self-contained C program that replays it:
arena mmap, copyins (including bitfields, result back-references and
runtime inet checksums), the call sequence with result tracking, and
an option matrix for repetition / multi-process / threaded execution /
fault injection / sandboxing (reference: pkg/csource/csource.go:17
Write, 299 generateCalls; options matrix pkg/csource/options.go:15-39).

Linux targets emit raw syscall(NR, ...) invocations; the hermetic
"test" target emits calls through a stub sim_call() so generated
sources always compile.
"""

from __future__ import annotations

from dataclasses import dataclass

from syzkaller_tpu.models.checksum import (CsumChunkKind, CsumKind,
                                           calc_checksums_call)
from syzkaller_tpu.models.prog import (Arg, ConstArg, DataArg, GroupArg,
                                       PointerArg, Prog, ResultArg, UnionArg,
                                       foreach_arg)
from syzkaller_tpu.models.types import (CsumType, Dir, ProcType, is_pad)


@dataclass
class Options:
    """(reference: pkg/csource/options.go:15-39)"""
    threaded: bool = False
    collide: bool = False
    repeat: bool = False
    procs: int = 1
    sandbox: str = "none"  # none | setuid | namespace
    fault: bool = False
    fault_call: int = -1
    fault_nth: int = 0
    use_tmp_dir: bool = True
    tun: bool = False      # tap-device packet injection env
    cgroups: bool = False  # per-proc cgroup join

    def serialize(self) -> str:
        """One-line option descriptor stored with repro artifacts
        (reference: options.go Serialize)."""
        return ("{" + f"threaded:{self.threaded} collide:{self.collide} "
                f"repeat:{self.repeat} procs:{self.procs} "
                f"sandbox:{self.sandbox} fault:{self.fault} "
                f"fault_call:{self.fault_call} fault_nth:{self.fault_nth} "
                f"tun:{self.tun} cgroups:{self.cgroups}"
                + "}")

    @staticmethod
    def deserialize(s: str) -> "Options":
        opts = Options()
        for tok in s.strip("{}\n ").split():
            k, _, v = tok.partition(":")
            if not hasattr(opts, k):
                continue
            cur = getattr(opts, k)
            if isinstance(cur, bool):
                setattr(opts, k, v == "True" or v == "true")
            elif isinstance(cur, int):
                setattr(opts, k, int(v))
            else:
                setattr(opts, k, v)
        return opts


def write_csource(p: Prog, opts: Options | None = None) -> bytes:
    opts = opts or Options()
    return _Renderer(p, opts).render().encode()


class _Renderer:
    def __init__(self, p: Prog, opts: Options):
        self.p = p
        self.opts = opts
        self.target = p.target
        self.lines: list[str] = []
        self.res_index: dict[int, int] = {}  # id(ResultArg) -> r[] slot
        self._assign_results()

    def _assign_results(self) -> None:
        n = 0
        for c in self.p.calls:
            if c.ret is not None and len(c.ret.uses) != 0:
                self.res_index[id(c.ret)] = n
                n += 1

            def visit(arg: Arg, ctx) -> None:
                nonlocal n
                if isinstance(arg, ResultArg) and len(arg.uses) != 0 \
                        and id(arg) not in self.res_index:
                    self.res_index[id(arg)] = n
                    n += 1

            foreach_arg(c, visit)
        self.nres = n

    # -- rendering --------------------------------------------------------

    def render(self) -> str:
        header = _HEADER
        if self.target.os in ("linux", "freebsd", "netbsd"):
            # real-OS backends share the raw-syscall(2) rendering; the
            # namespace/TUN/cgroup helpers in the templates are
            # __linux__-guarded so the same output compiles on a BSD
            # toolchain (reference analog: per-OS common_*.h split,
            # executor/common_bsd.h)
            backend = _LINUX_BACKEND
        else:
            backend = _SIM_BACKEND
        body = self._render_body()
        main = self._render_main()
        pseudo = self._render_pseudo_helpers()
        return "\n".join([header, backend, pseudo, body, main, ""])

    def _used_pseudo(self) -> set[str]:
        return {c.meta.call_name for c in self.p.calls
                if c.meta.call_name in _PSEUDO_C}

    def _render_pseudo_helpers(self) -> str:
        """C implementations for the syz_* calls the program uses
        (reference: csource embeds executor/common_linux.h's syz_*
        bodies the same way)."""
        if self.target.os != "linux":
            return ""
        used = self._used_pseudo()
        out = []
        if self.opts.tun or used & {"syz_emit_ethernet",
                                    "syz_extract_tcp_res"}:
            out.append(_C_TUN)
        if used & {"syz_fuse_mount", "syz_fuseblk_mount"}:
            out.append(_C_FUSE_OPTS)
        for name in sorted(used):
            out.append(_PSEUDO_C[name])
        return "\n".join(out)

    def _render_body(self) -> str:
        out = []
        if self.nres:
            out.append(f"static intptr_t r[{self.nres}];")
        out.append("static void execute_one(void)\n{")
        if self.nres:
            out.append(f"  for (int i = 0; i < {self.nres}; i++) "
                       "r[i] = -1;")
        for ci, c in enumerate(self.p.calls):
            out.append(f"  // {c.meta.name}")
            out.extend(self._render_copyins(c))
            if self.opts.fault and self.opts.fault_call == ci:
                out.append(f"  inject_fault({self.opts.fault_nth});")
            out.append("  " + self._render_call(ci, c))
        out.append("}")
        return "\n".join(out)

    def _render_copyins(self, c) -> list[str]:
        target = self.target
        out: list[str] = []
        csum_map = calc_checksums_call(c)
        csum_args: dict[int, int] = {}  # id(arg) -> addr, for csum pass

        def copyin(arg: Arg, ctx) -> None:
            if ctx.base is None:
                return
            addr = target.physical_addr(ctx.base) + ctx.offset
            if isinstance(arg, (GroupArg, UnionArg)):
                return
            csum_args[id(arg)] = addr
            t = arg.typ
            if t.dir == Dir.OUT or is_pad(t) or arg.size() == 0:
                return
            if isinstance(arg, DataArg):
                if not arg.data:
                    return
                lit = "".join(f"\\x{b:02x}" for b in arg.data)
                out.append(f'  NONFAILING(memcpy((void*)0x{addr:x}, '
                           f'"{lit}", {len(arg.data)}));')
            elif isinstance(arg, ResultArg):
                expr = self._result_expr(arg)
                out.append(self._store(addr, arg.size(), expr, t))
            elif isinstance(arg, ConstArg):
                if isinstance(t, CsumType):
                    return  # filled by the csum pass below
                val, pid_stride, big_endian = arg.value()
                expr = f"0x{val:x}"
                if pid_stride:
                    expr += f" + procid*{pid_stride}"
                if big_endian and arg.size() > 1:
                    expr = f"htobe{arg.size() * 8}({expr})"
                out.append(self._store(addr, arg.size(), expr, t))

        foreach_arg(c, copyin)

        if csum_map is not None:
            entries = sorted(csum_map.values(),
                             key=lambda e: csum_args[id(e[0])])
            for arg, info in reversed(entries):
                addr = csum_args[id(arg)]
                assert info.kind == CsumKind.INET
                out.append("  {\n    struct csum_inet csum;\n"
                           "    csum_inet_init(&csum);")
                for chunk in info.chunks:
                    if chunk.kind == CsumChunkKind.ARG:
                        caddr = csum_args[id(chunk.arg)]
                        out.append(f"    csum_inet_update(&csum, "
                                   f"(const uint8_t*)0x{caddr:x}, "
                                   f"{chunk.arg.size()});")
                    else:
                        out.append(f"    uint64_t w{addr:x} = "
                                   f"0x{chunk.value:x};\n"
                                   f"    csum_inet_update(&csum, "
                                   f"(const uint8_t*)&w{addr:x}, "
                                   f"{chunk.size});")
                out.append(f"    NONFAILING(*(uint16_t*)0x{addr:x} = "
                           "csum_inet_digest(&csum));\n  }")
        return out

    def _store(self, addr: int, size: int, expr: str, t) -> str:
        bf_off = getattr(t, "bitfield_off", 0)
        bf_len = getattr(t, "bitfield_len", 0)
        if bf_len:
            return (f"  NONFAILING(STORE_BY_BITMASK(uint{t.size * 8}_t, "
                    f"0x{addr:x}, {expr}, {bf_off}, {bf_len}));")
        ctype = {1: "uint8_t", 2: "uint16_t", 4: "uint32_t",
                 8: "uint64_t"}.get(size, "uint64_t")
        return f"  NONFAILING(*({ctype}*)0x{addr:x} = {expr});"

    def _result_expr(self, arg: ResultArg) -> str:
        if arg.res is None:
            return f"0x{arg.val:x}"
        idx = self.res_index.get(id(arg.res))
        if idx is None:
            return f"0x{arg.typ.default():x}" \
                if hasattr(arg.typ, "default") else "-1"
        expr = f"r[{idx}]"
        if getattr(arg, "op_div", 0):
            expr = f"({expr}/{arg.op_div})"
        if getattr(arg, "op_add", 0):
            expr = f"({expr}+{arg.op_add})"
        return expr

    def _render_call(self, ci: int, c) -> str:
        args = []
        for arg in c.args:
            args.append(self._scalar(arg))
        ret = ""
        if c.ret is not None and id(c.ret) in self.res_index:
            ret = f"r[{self.res_index[id(c.ret)]}] = "
        if self.target.os == "linux" and \
                c.meta.call_name in _PSEUDO_C:
            call = f"{c.meta.call_name}("
            call += ", ".join(f"(long)({a})" for a in args)
            call += ")"
        elif self.target.os in ("linux", "freebsd", "netbsd"):
            call = f"tz_syscall({c.meta.nr}"
            if args:
                call += ", " + ", ".join(args)
            call += ")"
        else:
            call = f"sim_call({c.meta.nr}"
            for a in args:
                call += f", (intptr_t)({a})"
            call += ")"
        return f"{ret}{call};"

    def _scalar(self, arg: Arg) -> str:
        if isinstance(arg, PointerArg):
            if arg.is_null():
                return "0"
            return f"0x{self.target.physical_addr(arg):x}"
        if isinstance(arg, ResultArg):
            return self._result_expr(arg)
        if isinstance(arg, ConstArg):
            val, pid_stride, _ = arg.value()
            expr = f"0x{val:x}"
            if pid_stride:
                expr += f" + procid*{pid_stride}"
            return expr
        if isinstance(arg, UnionArg):
            return self._scalar(arg.option)
        return "0"

    def _render_main(self) -> str:
        o = self.opts
        out = ["int main(void)\n{"]
        base = self.target.data_offset
        size = self.target.num_pages * self.target.page_size
        out.append(f"  mmap((void*)0x{base:x}, 0x{size:x}, "
                   "PROT_READ|PROT_WRITE, "
                   "MAP_ANONYMOUS|MAP_PRIVATE|MAP_FIXED, -1, 0);")
        if o.use_tmp_dir:
            out.append("  use_temporary_dir();")
        out.append(f"  install_segv_handler();")
        if o.sandbox == "namespace":
            out.append("  sandbox_namespace();")
        # per-PROC env setup (tap fd, cgroup dir) runs after the fork
        # so each proc gets its own procid-keyed instances; the
        # privilege drop comes last, in the proc itself
        proc_setup = []
        if self.target.os == "linux" and (
                o.tun or self._used_pseudo() & {"syz_emit_ethernet",
                                                "syz_extract_tcp_res"}):
            proc_setup.append("setup_tun();")
        if o.cgroups:
            proc_setup.append("setup_cgroups();")
        if o.sandbox == "setuid":
            proc_setup.append("sandbox_setuid();")
        # Sweep only single-proc repros inside a tmp-dir sandbox: with
        # procs > 1 the children share one cwd and a sweeping sibling
        # would detach another proc's live mount mid-iteration.
        sweep = ""
        if "syz_mount_image" in self._used_pseudo() and o.use_tmp_dir \
                and o.procs <= 1:
            sweep = " tz_unmount_all();"
        loop_body = f"execute_one();{sweep}"
        if o.repeat:
            loop_body = f"for (;;) {{ execute_one();{sweep} }}"
        if o.procs > 1:
            out.append(f"  for (procid = 0; procid < {o.procs}; "
                       "procid++) {")
            out.append("    if (fork() == 0) {")
            for s in proc_setup:
                out.append(f"      {s}")
            out.append(f"      {loop_body}")
            out.append("      exit(0);")
            out.append("    }")
            out.append("  }")
            out.append("  sleep(1000000);")
        else:
            for s in proc_setup:
                out.append(f"  {s}")
            out.append(f"  {loop_body}")
        out.append("  return 0;\n}")
        return "\n".join(out)


_HEADER = r"""// autogenerated C reproducer
#define _GNU_SOURCE
#if defined(__FreeBSD__) || defined(__NetBSD__)
#include <sys/endian.h>
#else
#include <endian.h>
#endif
// FreeBSD's syscall(2) returns int — 64-bit results (mmap addresses,
// lseek offsets) would truncate; __syscall is the 64-bit-clean form.
#if defined(__FreeBSD__)
#define tz_syscall __syscall
#else
#define tz_syscall syscall
#endif
#include <errno.h>
#include <fcntl.h>
#include <setjmp.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

static int procid;

#define STORE_BY_BITMASK(type, addr, val, bf_off, bf_len)             \
  do {                                                                \
    type __v = *(type*)(addr);                                        \
    __v &= ~(((((type)1 << (bf_len)) - 1)) << (bf_off));              \
    __v |= ((type)(val) & (((type)1 << (bf_len)) - 1)) << (bf_off);   \
    *(type*)(addr) = __v;                                             \
  } while (0)

// tolerate wild stores into unmapped corners of the arena: every
// copyin runs under NONFAILING, which arms the jump buffer before the
// handler can fire (reference: executor/common.h NONFAILING)
static __thread sigjmp_buf segv_env;
static __thread int segv_armed;
#define NONFAILING(...)                         \
  do {                                          \
    segv_armed = 1;                             \
    if (sigsetjmp(segv_env, 1) == 0) {          \
      __VA_ARGS__;                              \
    }                                           \
    segv_armed = 0;                             \
  } while (0)
static void segv_handler(int sig)
{
  (void)sig;
  if (segv_armed) siglongjmp(segv_env, 1);
  _exit(sig);
}
static void install_segv_handler(void)
{
  struct sigaction sa;
  memset(&sa, 0, sizeof(sa));
  sa.sa_handler = segv_handler;
  sigaction(SIGSEGV, &sa, NULL);
  sigaction(SIGBUS, &sa, NULL);
}

static void use_temporary_dir(void)
{
  char tmpdir_template[] = "./syzkaller.XXXXXX";
  char* tmpdir = mkdtemp(tmpdir_template);
  if (!tmpdir) return;
  if (chmod(tmpdir, 0777)) {}
  if (chdir(tmpdir)) {}
}

static void sandbox_setuid(void)
{
  if (setgid(65534)) {}
  if (setuid(65534)) {}
}

#ifdef __linux__
#include <sched.h>
#include <sys/mount.h>
static void write_str_file(const char* path, const char* data)
{
  int fd = open(path, O_WRONLY);
  if (fd < 0) return;
  if (write(fd, data, strlen(data))) {}
  close(fd);
}
// fresh user/mount/net/ipc/uts namespaces, uid 0 inside
// (executor/pseudo_linux.h sandbox_namespace twin)
static void sandbox_namespace(void)
{
  int uid = getuid(), gid = getgid();
  char buf[64];
  if (unshare(CLONE_NEWUSER | CLONE_NEWNS | CLONE_NEWNET |
              CLONE_NEWIPC | CLONE_NEWUTS) == 0) {
    write_str_file("/proc/self/setgroups", "deny");
    snprintf(buf, sizeof(buf), "0 %d 1", uid);
    write_str_file("/proc/self/uid_map", buf);
    snprintf(buf, sizeof(buf), "0 %d 1", gid);
    write_str_file("/proc/self/gid_map", buf);
  } else if (unshare(CLONE_NEWNS | CLONE_NEWNET | CLONE_NEWIPC |
                     CLONE_NEWUTS)) {
    return;
  }
  if (mount(NULL, "/", NULL, MS_REC | MS_PRIVATE, NULL)) {}
}
static void setup_cgroups(void)
{
  char dir[64], self[32];
  snprintf(dir, sizeof(dir), "/sys/fs/cgroup/tz%d", procid);
  if (mkdir(dir, 0777) && errno != EEXIST) return;
  char procs[96];
  snprintf(procs, sizeof(procs), "%s/cgroup.procs", dir);
  snprintf(self, sizeof(self), "%d", getpid());
  write_str_file(procs, self);
}
#else
static void sandbox_namespace(void) {}
static void setup_cgroups(void) {}
#endif

struct csum_inet {
  uint32_t acc;
};
static void csum_inet_init(struct csum_inet* csum) { csum->acc = 0; }
static void csum_inet_update(struct csum_inet* csum, const uint8_t* data,
                             size_t length)
{
  if (length == 0) return;
  size_t i;
  for (i = 0; i < length - 1; i += 2)
    csum->acc += *(uint16_t*)&data[i];
  if (length & 1) csum->acc += (uint16_t)data[length - 1];
  while (csum->acc > 0xffff)
    csum->acc = (csum->acc & 0xffff) + (csum->acc >> 16);
}
static uint16_t csum_inet_digest(struct csum_inet* csum)
{
  return ~csum->acc;
}

static void inject_fault(int nth)
{
  // fail-nth via procfs when available (reference:
  // executor/common_linux.h fault injection setup)
  int fd = open("/proc/thread-self/fail-nth", O_RDWR);
  if (fd < 0) return;
  char buf[16];
  snprintf(buf, sizeof(buf), "%d", nth + 1);
  if (write(fd, buf, strlen(buf))) {}
  close(fd);
}"""

_LINUX_BACKEND = r"""// direct syscall backend"""

_SIM_BACKEND = r"""// hermetic test-target backend: calls are logged no-ops so the
// reproducer structure (copyins, dataflow, options) stays verifiable
static intptr_t sim_call(intptr_t nr, ...)
{
  return nr >= 0 ? 0 : -1;
}"""

# ---- syz_* pseudo-syscall C bodies (executor/pseudo_linux.h twins;
# reference: csource embeds common_linux.h) --------------------------

_C_TUN = r"""#include <arpa/inet.h>
#include <linux/if.h>
#include <linux/if_tun.h>
#include <sys/ioctl.h>
#include <sys/socket.h>
static int tun_fd = -1;
static void setup_tun(void)
{
  tun_fd = open("/dev/net/tun", O_RDWR | O_NONBLOCK);
  if (tun_fd < 0) return;
  struct ifreq ifr;
  memset(&ifr, 0, sizeof(ifr));
  snprintf(ifr.ifr_name, IFNAMSIZ, "tz_tun%d", procid);
  ifr.ifr_flags = IFF_TAP | IFF_NO_PI;
  if (ioctl(tun_fd, TUNSETIFF, &ifr)) { close(tun_fd); tun_fd = -1; return; }
  int sock = socket(AF_INET, SOCK_DGRAM, 0);
  if (sock >= 0) {
    ioctl(sock, SIOCGIFFLAGS, &ifr);
    ifr.ifr_flags |= IFF_UP | IFF_RUNNING;
    ioctl(sock, SIOCSIFFLAGS, &ifr);
    close(sock);
  }
}"""

# shared option-string builder for the two fuse mount helpers
_C_FUSE_OPTS = r"""// fuse mount option string (executor twin: pseudo_linux.h fuse_opts)
static void tz_fuse_opts(char* buf, size_t cap, int fd, long mode,
                         long uid, long gid, long maxread, long blksize)
{
  size_t n = (size_t)snprintf(buf, cap,
      "fd=%d,user_id=%lu,group_id=%lu,rootmode=0%o", fd,
      (unsigned long)uid, (unsigned long)gid, (unsigned)mode & ~3u);
  if (maxread && n < cap)
    n += (size_t)snprintf(buf + n, cap - n, ",max_read=%lu",
                          (unsigned long)maxread);
  if (blksize && n < cap)
    n += (size_t)snprintf(buf + n, cap - n, ",blksize=%lu",
                          (unsigned long)blksize);
  if ((mode & 1) && n < cap)
    n += (size_t)snprintf(buf + n, cap - n, ",default_permissions");
  if ((mode & 2) && n < cap)
    n += (size_t)snprintf(buf + n, cap - n, ",allow_other");
}"""

_PSEUDO_C = {
    "syz_fuse_mount": r"""// open /dev/fuse + mount a fs driven by that fd; mount errors are
// ignored, the fd alone is useful (executor twin: pseudo_fuse_mount)
static long syz_fuse_mount(long target, long mode, long uid, long gid,
                           long maxread, long flags)
{
  char opts[256];
  int fd = open("/dev/fuse", O_RDWR);
  if (fd < 0) return fd;
  mkdir((char*)target, 0777);
  tz_fuse_opts(opts, sizeof(opts), fd, mode, uid, gid, maxread, 0);
  mount("", (char*)target, "fuse", flags, opts);
  return fd;
}""",
    "syz_fuseblk_mount": r"""#include <sys/sysmacros.h>
static long syz_fuseblk_mount(long target, long blkdev, long mode,
                              long uid, long gid, long maxread,
                              long blksize, long flags)
{
  char opts[256];
  int fd = open("/dev/fuse", O_RDWR);
  if (fd < 0) return fd;
  if (mknod((char*)blkdev, S_IFBLK | 0600, makedev(7, 199)) &&
      errno != EEXIST)
    return fd;
  mkdir((char*)target, 0777);
  tz_fuse_opts(opts, sizeof(opts), fd, mode, uid, gid, maxread,
               blksize);
  mount((char*)blkdev, (char*)target, "fuseblk", flags, opts);
  return fd;
}""",
    "syz_init_net_socket": r"""#include <sched.h>
// socket() in the init net namespace; falls back to the current ns
// (executor twin: pseudo_init_net_socket)
static long syz_init_net_socket(long family, long type, long proto)
{
  long fd;
  int self_ns = open("/proc/self/ns/net", O_RDONLY);
  int init_ns = open("/proc/1/ns/net", O_RDONLY);
  int hopped = self_ns >= 0 && init_ns >= 0 &&
               setns(init_ns, CLONE_NEWNET) == 0;
  fd = socket(family, type, proto);
  if (hopped) setns(self_ns, CLONE_NEWNET);
  if (self_ns >= 0) close(self_ns);
  if (init_ns >= 0) close(init_ns);
  return fd;
}""",
    "syz_open_dev": r"""static long syz_open_dev(long name, long id, long flags)
{
  char buf[256], *hash;
  snprintf(buf, sizeof(buf), "%s", (char*)name);
  hash = strchr(buf, '#');
  if (hash) {
    char tail[128];
    snprintf(tail, sizeof(tail), "%s", hash + 1);
    snprintf(hash, sizeof(buf) - (hash - buf), "%d%s", (int)id, tail);
  }
  return open(buf, flags, 0666);
}""",
    "syz_open_procfs": r"""static long syz_open_procfs(long pid, long file)
{
  char buf[160];
  if (pid == 0)
    snprintf(buf, sizeof(buf), "/proc/self/%s", (char*)file);
  else
    snprintf(buf, sizeof(buf), "/proc/%d/%s", (int)pid, (char*)file);
  int fd = open(buf, O_RDWR);
  if (fd < 0) fd = open(buf, O_RDONLY);
  return fd;
}""",
    "syz_open_pts": r"""#include <sys/ioctl.h>
static long syz_open_pts(long master, long flags)
{
  int ptyno = 0;
  if (ioctl((int)master, TIOCGPTN, &ptyno)) return -1;
  char buf[32];
  snprintf(buf, sizeof(buf), "/dev/pts/%d", ptyno);
  return open(buf, flags);
}""",
    "syz_emit_ethernet": r"""static long syz_emit_ethernet(long len, long packet)
{
  if (tun_fd < 0) return -1;
  return write(tun_fd, (void*)packet, len);
}""",
    "syz_extract_tcp_res": r"""static long syz_extract_tcp_res(long res, long seq_inc, long ack_inc)
{
  if (tun_fd < 0) return -1;
  unsigned char pkt[2048];
  int n = read(tun_fd, pkt, sizeof(pkt));
  if (n < 14 + 20 + 20) return -1;
  if (pkt[12] != 0x08 || pkt[13] != 0x00) return -1;
  int ihl = (pkt[14] & 0xf) * 4;
  if (pkt[14 + 9] != 6 || n < 14 + ihl + 20) return -1;
  uint32_t seq, ack;
  memcpy(&seq, pkt + 14 + ihl + 4, 4);
  memcpy(&ack, pkt + 14 + ihl + 8, 4);
  seq = htonl(ntohl(seq) + (uint32_t)seq_inc);
  ack = htonl(ntohl(ack) + (uint32_t)ack_inc);
  memcpy((void*)res, &seq, 4);
  memcpy((void*)(res + 4), &ack, 4);
  return 0;
}""",
    "syz_genetlink_get_family_id":
        r"""#include <linux/netlink.h>
#include <sys/socket.h>
static long syz_genetlink_get_family_id(long name)
{
  int sock = socket(AF_NETLINK, SOCK_RAW, 16);
  if (sock < 0) return -1;
  struct {
    struct nlmsghdr hdr;
    uint8_t cmd, version; uint16_t reserved;
    uint16_t attr_len, attr_type;
    char attr[64];
  } __attribute__((packed)) req;
  memset(&req, 0, sizeof(req));
  size_t name_len = strlen((char*)name) + 1;
  if (name_len > sizeof(req.attr)) name_len = sizeof(req.attr);
  req.hdr.nlmsg_type = 0x10;
  req.hdr.nlmsg_flags = NLM_F_REQUEST;
  req.cmd = 3; req.version = 1;
  req.attr_type = 2;
  memcpy(req.attr, (char*)name, name_len);
  req.attr_len = 4 + name_len;
  req.hdr.nlmsg_len = 20 + ((req.attr_len + 3) & ~3u);
  long ret = -1;
  if (send(sock, &req, req.hdr.nlmsg_len, 0) >= 0) {
    uint8_t buf[4096];
    int got = recv(sock, buf, sizeof(buf), 0);
    size_t off = 20;
    while (got >= 24 && off + 4 <= (size_t)got) {
      uint16_t alen, atype;
      memcpy(&alen, buf + off, 2);
      memcpy(&atype, buf + off + 2, 2);
      if (alen < 4) break;
      if (atype == 1 && alen >= 6) {
        uint16_t id; memcpy(&id, buf + off + 4, 2); ret = id; break;
      }
      off += (alen + 3) & ~3u;
    }
  }
  close(sock);
  return ret;
}""",
    "syz_mount_image": r"""#include <linux/loop.h>
#include <sys/ioctl.h>
#include <sys/mount.h>
struct tz_img_segment { uint64_t addr, size, offset; };
// Mirrors the executor's pseudo_mount_image clamps (pseudo_linux.h
// build_image): 64MB image cap, <=64 segments of <=1MB bounded to the
// image, mountpoint confined to the basename under the cwd — so a
// repro behaves like the fuzzed execution and a mutated huge size
// cannot exhaust the repro host's disk.
static long syz_mount_image(long fs, long dir, long size, long nsegs,
                            long segs, long flags, long opts)
{
  char tmpl[] = "/tmp/tz_img_XXXXXX";
  int img = mkstemp(tmpl);
  if (img < 0) return -1;
  unlink(tmpl);
  if ((uint64_t)size > (64ull << 20)) size = 64ll << 20;
  if (ftruncate(img, size)) { close(img); return -1; }
  struct tz_img_segment* seg = (struct tz_img_segment*)segs;
  for (long i = 0; i < nsegs && i < 64; i++) {
    uint64_t ssize = seg[i].size, soff = seg[i].offset;
    if (ssize > (1 << 20) || soff > (uint64_t)size) continue;
    if (soff + ssize > (uint64_t)size) ssize = size - soff;
    if (pwrite(img, (void*)seg[i].addr, ssize, soff)) {}
  }
  int ctl = open("/dev/loop-control", O_RDWR);
  if (ctl < 0) { close(img); return -1; }
  int idx = ioctl(ctl, LOOP_CTL_GET_FREE);
  close(ctl);
  if (idx < 0) { close(img); return -1; }
  char ldev[32];
  snprintf(ldev, sizeof(ldev), "/dev/loop%d", idx);
  int lfd = open(ldev, O_RDWR);
  if (lfd < 0) { close(img); return -1; }
  if (ioctl(lfd, LOOP_SET_FD, img)) { close(lfd); close(img); return -1; }
  close(img);
  // AUTOCLEAR: the kernel frees the loop device when its last user
  // (the mount, or our fd) goes away — no leak under repeat mode
  struct loop_info64 info;
  memset(&info, 0, sizeof(info));
  if (ioctl(lfd, LOOP_GET_STATUS64, &info)) {
    ioctl(lfd, LOOP_CLR_FD, 0);
    close(lfd);
    return -1;
  }
  info.lo_flags |= LO_FLAGS_AUTOCLEAR;
  if (ioctl(lfd, LOOP_SET_STATUS64, &info)) {
    // without AUTOCLEAR the device would outlive every user: detach
    ioctl(lfd, LOOP_CLR_FD, 0);
    close(lfd);
    return -1;
  }
  // copy under NONFAILING: dir may be NULL/unmapped (EFAULT in the
  // fuzzed run must not become a repro-killing segfault here)
  char dbuf[64];
  dbuf[0] = 0;
  NONFAILING(strncpy(dbuf, (char*)dir, sizeof(dbuf) - 1));
  dbuf[sizeof(dbuf) - 1] = 0;
  const char* rbase = strrchr(dbuf, '/');
  rbase = rbase ? rbase + 1 : dbuf;
  if (!rbase[0] || !strcmp(rbase, ".") || !strcmp(rbase, ".."))
    rbase = "m";  // keep the mount confined to the cwd
  char mdir[160];
  snprintf(mdir, sizeof(mdir), "./%s", rbase);
  mkdir(mdir, 0777);
  long res = mount(ldev, mdir, (char*)fs, flags,
                   opts ? (char*)opts : NULL);
  close(lfd);
  if (res < 0) return res;
  return open(mdir, O_RDONLY | O_DIRECTORY);
}
// End-of-iteration sweep: unmount everything mounted under the cwd so
// repeat mode reuses mountpoints and a one-shot repro exits clean
// (executor twin: pseudo_linux.h pseudo_cleanup/pseudo_parent_sweep).
static void tz_unmount_all(void)
{
  char cwd[256];
  if (!getcwd(cwd, sizeof(cwd))) return;
  // only sweep inside a use_temporary_dir() sandbox: if mkdtemp/chdir
  // failed (or the repro was built without a tmp dir) the cwd is the
  // user's own directory and their mounts must not be touched
  const char* cb = strrchr(cwd, '/');
  if (!cb || strncmp(cb + 1, "syzkaller.", 10)) return;
  size_t n = strlen(cwd);
  for (int pass = 0; pass < 4; pass++) {
    FILE* f = fopen("/proc/self/mounts", "r");
    if (!f) return;
    char line[512];
    int any = 0;
    while (fgets(line, sizeof(line), f)) {
      char* sp = strchr(line, ' ');
      if (!sp) continue;
      char* mnt = sp + 1;
      char* end = strchr(mnt, ' ');
      if (!end) continue;
      *end = 0;
      // /proc/self/mounts octal-escapes space/tab/newline (\040...)
      char dec[512];
      size_t di = 0;
      for (char* c = mnt; *c && di < sizeof(dec) - 1; c++, di++) {
        if (c[0] == '\\' && c[1] >= '0' && c[1] <= '3' &&
            c[2] >= '0' && c[2] <= '7' && c[3] >= '0' && c[3] <= '7') {
          dec[di] = (char)((c[1] - '0') * 64 + (c[2] - '0') * 8 +
                           (c[3] - '0'));
          c += 3;
        } else {
          dec[di] = c[0];
        }
      }
      dec[di] = 0;
      if (strncmp(dec, cwd, n) == 0 && dec[n] == '/' &&
          umount2(dec, MNT_DETACH) == 0)
        any = 1;
    }
    fclose(f);
    if (!any) break;
  }
}""",
    "syz_read_part_table": r"""#include <linux/fs.h>
#include <linux/loop.h>
#include <sys/ioctl.h>
struct tz_rpt_segment { uint64_t addr, size, offset; };
static long syz_read_part_table(long size, long nsegs, long segs)
{
  char tmpl[] = "/tmp/tz_img_XXXXXX";
  int img = mkstemp(tmpl);
  if (img < 0) return -1;
  unlink(tmpl);
  if ((uint64_t)size > (64ull << 20)) size = 64ll << 20;
  if (ftruncate(img, size)) { close(img); return -1; }
  struct tz_rpt_segment* seg = (struct tz_rpt_segment*)segs;
  for (long i = 0; i < nsegs && i < 64; i++) {
    uint64_t ssize = seg[i].size, soff = seg[i].offset;
    if (ssize > (1 << 20) || soff > (uint64_t)size) continue;
    if (soff + ssize > (uint64_t)size) ssize = size - soff;
    if (pwrite(img, (void*)seg[i].addr, ssize, soff)) {}
  }
  int ctl = open("/dev/loop-control", O_RDWR);
  if (ctl < 0) { close(img); return -1; }
  int idx = ioctl(ctl, LOOP_CTL_GET_FREE);
  close(ctl);
  if (idx < 0) { close(img); return -1; }
  char ldev[32];
  snprintf(ldev, sizeof(ldev), "/dev/loop%d", idx);
  int lfd = open(ldev, O_RDWR);
  if (lfd < 0) { close(img); return -1; }
  long res = -1;
  if (ioctl(lfd, LOOP_SET_FD, img) == 0) {
    res = ioctl(lfd, BLKRRPART, 0);
    ioctl(lfd, LOOP_CLR_FD, 0);
  }
  close(lfd);
  close(img);
  return res;
}""",
    "syz_kvm_setup_cpu": r"""#include <linux/kvm.h>
#include <sys/ioctl.h>
struct tz_kvm_text { uint64_t typ, text, len; };
static long syz_kvm_setup_cpu(long vmfd, long cpufd, long usermem,
                              long text, long ntext, long flags)
{
  if (ntext == 0) return -1;
  struct tz_kvm_text* seg = (struct tz_kvm_text*)text;
  struct kvm_userspace_memory_region mem;
  memset(&mem, 0, sizeof(mem));
  mem.memory_size = 24 << 12;
  mem.userspace_addr = (uint64_t)usermem;
  if (ioctl(vmfd, KVM_SET_USER_MEMORY_REGION, &mem)) return -1;
  memset((void*)usermem, 0xf4, 0x2000);
  uint64_t len = seg->len > 0x1000 ? 0x1000 : seg->len;
  memcpy((char*)usermem + 0x1000, (void*)seg->text, len);
  struct kvm_sregs sregs;
  if (ioctl(cpufd, KVM_GET_SREGS, &sregs)) return -1;
  struct kvm_regs regs;
  memset(&regs, 0, sizeof(regs));
  regs.rflags = 2;
  sregs.cs.base = 0x1000; sregs.cs.selector = 0x100;
  regs.rip = 0; regs.rsp = 0xf000;
  if (ioctl(cpufd, KVM_SET_SREGS, &sregs)) return -1;
  if (ioctl(cpufd, KVM_SET_REGS, &regs)) return -1;
  return 0;
}""",
}
