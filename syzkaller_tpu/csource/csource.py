"""Standalone C reproducer generation.

Renders a typed Prog into a self-contained C program that replays it:
arena mmap, copyins (including bitfields, result back-references and
runtime inet checksums), the call sequence with result tracking, and
an option matrix for repetition / multi-process / threaded execution /
fault injection / sandboxing (reference: pkg/csource/csource.go:17
Write, 299 generateCalls; options matrix pkg/csource/options.go:15-39).

Linux targets emit raw syscall(NR, ...) invocations; the hermetic
"test" target emits calls through a stub sim_call() so generated
sources always compile.
"""

from __future__ import annotations

from dataclasses import dataclass

from syzkaller_tpu.models.checksum import (CsumChunkKind, CsumKind,
                                           calc_checksums_call)
from syzkaller_tpu.models.prog import (Arg, ConstArg, DataArg, GroupArg,
                                       PointerArg, Prog, ResultArg, UnionArg,
                                       foreach_arg)
from syzkaller_tpu.models.types import (CsumType, Dir, ProcType, is_pad)


@dataclass
class Options:
    """(reference: pkg/csource/options.go:15-39)"""
    threaded: bool = False
    collide: bool = False
    repeat: bool = False
    procs: int = 1
    sandbox: str = "none"
    fault: bool = False
    fault_call: int = -1
    fault_nth: int = 0
    use_tmp_dir: bool = True

    def serialize(self) -> str:
        """One-line option descriptor stored with repro artifacts
        (reference: options.go Serialize)."""
        return ("{" + f"threaded:{self.threaded} collide:{self.collide} "
                f"repeat:{self.repeat} procs:{self.procs} "
                f"sandbox:{self.sandbox} fault:{self.fault} "
                f"fault_call:{self.fault_call} fault_nth:{self.fault_nth}"
                + "}")

    @staticmethod
    def deserialize(s: str) -> "Options":
        opts = Options()
        for tok in s.strip("{}\n ").split():
            k, _, v = tok.partition(":")
            if not hasattr(opts, k):
                continue
            cur = getattr(opts, k)
            if isinstance(cur, bool):
                setattr(opts, k, v == "True" or v == "true")
            elif isinstance(cur, int):
                setattr(opts, k, int(v))
            else:
                setattr(opts, k, v)
        return opts


def write_csource(p: Prog, opts: Options | None = None) -> bytes:
    opts = opts or Options()
    return _Renderer(p, opts).render().encode()


class _Renderer:
    def __init__(self, p: Prog, opts: Options):
        self.p = p
        self.opts = opts
        self.target = p.target
        self.lines: list[str] = []
        self.res_index: dict[int, int] = {}  # id(ResultArg) -> r[] slot
        self._assign_results()

    def _assign_results(self) -> None:
        n = 0
        for c in self.p.calls:
            if c.ret is not None and len(c.ret.uses) != 0:
                self.res_index[id(c.ret)] = n
                n += 1

            def visit(arg: Arg, ctx) -> None:
                nonlocal n
                if isinstance(arg, ResultArg) and len(arg.uses) != 0 \
                        and id(arg) not in self.res_index:
                    self.res_index[id(arg)] = n
                    n += 1

            foreach_arg(c, visit)
        self.nres = n

    # -- rendering --------------------------------------------------------

    def render(self) -> str:
        header = _HEADER
        if self.target.os == "linux":
            backend = _LINUX_BACKEND
        else:
            backend = _SIM_BACKEND
        body = self._render_body()
        main = self._render_main()
        return "\n".join([header, backend, body, main, ""])

    def _render_body(self) -> str:
        out = []
        if self.nres:
            out.append(f"static intptr_t r[{self.nres}];")
        out.append("static void execute_one(void)\n{")
        if self.nres:
            out.append(f"  for (int i = 0; i < {self.nres}; i++) "
                       "r[i] = -1;")
        for ci, c in enumerate(self.p.calls):
            out.append(f"  // {c.meta.name}")
            out.extend(self._render_copyins(c))
            if self.opts.fault and self.opts.fault_call == ci:
                out.append(f"  inject_fault({self.opts.fault_nth});")
            out.append("  " + self._render_call(ci, c))
        out.append("}")
        return "\n".join(out)

    def _render_copyins(self, c) -> list[str]:
        target = self.target
        out: list[str] = []
        csum_map = calc_checksums_call(c)
        csum_args: dict[int, int] = {}  # id(arg) -> addr, for csum pass

        def copyin(arg: Arg, ctx) -> None:
            if ctx.base is None:
                return
            addr = target.physical_addr(ctx.base) + ctx.offset
            if isinstance(arg, (GroupArg, UnionArg)):
                return
            csum_args[id(arg)] = addr
            t = arg.typ
            if t.dir == Dir.OUT or is_pad(t) or arg.size() == 0:
                return
            if isinstance(arg, DataArg):
                if not arg.data:
                    return
                lit = "".join(f"\\x{b:02x}" for b in arg.data)
                out.append(f'  NONFAILING(memcpy((void*)0x{addr:x}, '
                           f'"{lit}", {len(arg.data)}));')
            elif isinstance(arg, ResultArg):
                expr = self._result_expr(arg)
                out.append(self._store(addr, arg.size(), expr, t))
            elif isinstance(arg, ConstArg):
                if isinstance(t, CsumType):
                    return  # filled by the csum pass below
                val, pid_stride, big_endian = arg.value()
                expr = f"0x{val:x}"
                if pid_stride:
                    expr += f" + procid*{pid_stride}"
                if big_endian:
                    expr = f"htobe{t.size * 8}({expr})" if t.size > 1 \
                        else expr
                out.append(self._store(addr, arg.size(), expr, t))

        foreach_arg(c, copyin)

        if csum_map is not None:
            entries = sorted(csum_map.values(),
                             key=lambda e: csum_args[id(e[0])])
            for arg, info in reversed(entries):
                addr = csum_args[id(arg)]
                assert info.kind == CsumKind.INET
                out.append("  {\n    struct csum_inet csum;\n"
                           "    csum_inet_init(&csum);")
                for chunk in info.chunks:
                    if chunk.kind == CsumChunkKind.ARG:
                        caddr = csum_args[id(chunk.arg)]
                        out.append(f"    csum_inet_update(&csum, "
                                   f"(const uint8_t*)0x{caddr:x}, "
                                   f"{chunk.arg.size()});")
                    else:
                        out.append(f"    uint64_t w{addr:x} = "
                                   f"0x{chunk.value:x};\n"
                                   f"    csum_inet_update(&csum, "
                                   f"(const uint8_t*)&w{addr:x}, "
                                   f"{chunk.size});")
                out.append(f"    NONFAILING(*(uint16_t*)0x{addr:x} = "
                           "csum_inet_digest(&csum));\n  }")
        return out

    def _store(self, addr: int, size: int, expr: str, t) -> str:
        bf_off = getattr(t, "bitfield_off", 0)
        bf_len = getattr(t, "bitfield_len", 0)
        if bf_len:
            return (f"  NONFAILING(STORE_BY_BITMASK(uint{t.size * 8}_t, "
                    f"0x{addr:x}, {expr}, {bf_off}, {bf_len}));")
        ctype = {1: "uint8_t", 2: "uint16_t", 4: "uint32_t",
                 8: "uint64_t"}.get(size, "uint64_t")
        return f"  NONFAILING(*({ctype}*)0x{addr:x} = {expr});"

    def _result_expr(self, arg: ResultArg) -> str:
        if arg.res is None:
            return f"0x{arg.val:x}"
        idx = self.res_index.get(id(arg.res))
        if idx is None:
            return f"0x{arg.typ.default():x}" \
                if hasattr(arg.typ, "default") else "-1"
        expr = f"r[{idx}]"
        if getattr(arg, "op_div", 0):
            expr = f"({expr}/{arg.op_div})"
        if getattr(arg, "op_add", 0):
            expr = f"({expr}+{arg.op_add})"
        return expr

    def _render_call(self, ci: int, c) -> str:
        args = []
        for arg in c.args:
            args.append(self._scalar(arg))
        ret = ""
        if c.ret is not None and id(c.ret) in self.res_index:
            ret = f"r[{self.res_index[id(c.ret)]}] = "
        if self.target.os == "linux":
            call = f"syscall({c.meta.nr}"
            if args:
                call += ", " + ", ".join(args)
            call += ")"
        else:
            call = f"sim_call({c.meta.nr}"
            for a in args:
                call += f", (intptr_t)({a})"
            call += ")"
        return f"{ret}{call};"

    def _scalar(self, arg: Arg) -> str:
        if isinstance(arg, PointerArg):
            if arg.is_null():
                return "0"
            return f"0x{self.target.physical_addr(arg):x}"
        if isinstance(arg, ResultArg):
            return self._result_expr(arg)
        if isinstance(arg, ConstArg):
            val, pid_stride, _ = arg.value()
            expr = f"0x{val:x}"
            if pid_stride:
                expr += f" + procid*{pid_stride}"
            return expr
        if isinstance(arg, UnionArg):
            return self._scalar(arg.option)
        return "0"

    def _render_main(self) -> str:
        o = self.opts
        out = ["int main(void)\n{"]
        base = self.target.data_offset
        size = self.target.num_pages * self.target.page_size
        out.append(f"  mmap((void*)0x{base:x}, 0x{size:x}, "
                   "PROT_READ|PROT_WRITE, "
                   "MAP_ANONYMOUS|MAP_PRIVATE|MAP_FIXED, -1, 0);")
        if o.use_tmp_dir:
            out.append("  use_temporary_dir();")
        out.append(f"  install_segv_handler();")
        if o.sandbox == "setuid":
            out.append("  sandbox_setuid();")
        loop_body = "execute_one();"
        if o.repeat:
            loop_body = "for (;;) { execute_one(); }"
        if o.procs > 1:
            out.append(f"  for (procid = 0; procid < {o.procs}; "
                       "procid++) {")
            out.append("    if (fork() == 0) {")
            out.append(f"      {loop_body}")
            out.append("      exit(0);")
            out.append("    }")
            out.append("  }")
            out.append("  sleep(1000000);")
        else:
            out.append(f"  {loop_body}")
        out.append("  return 0;\n}")
        return "\n".join(out)


_HEADER = r"""// autogenerated C reproducer
#define _GNU_SOURCE
#include <endian.h>
#include <errno.h>
#include <fcntl.h>
#include <setjmp.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

static int procid;

#define STORE_BY_BITMASK(type, addr, val, bf_off, bf_len)             \
  do {                                                                \
    type __v = *(type*)(addr);                                        \
    __v &= ~(((((type)1 << (bf_len)) - 1)) << (bf_off));              \
    __v |= ((type)(val) & (((type)1 << (bf_len)) - 1)) << (bf_off);   \
    *(type*)(addr) = __v;                                             \
  } while (0)

// tolerate wild stores into unmapped corners of the arena: every
// copyin runs under NONFAILING, which arms the jump buffer before the
// handler can fire (reference: executor/common.h NONFAILING)
static __thread sigjmp_buf segv_env;
static __thread int segv_armed;
#define NONFAILING(...)                         \
  do {                                          \
    segv_armed = 1;                             \
    if (sigsetjmp(segv_env, 1) == 0) {          \
      __VA_ARGS__;                              \
    }                                           \
    segv_armed = 0;                             \
  } while (0)
static void segv_handler(int sig)
{
  (void)sig;
  if (segv_armed) siglongjmp(segv_env, 1);
  _exit(sig);
}
static void install_segv_handler(void)
{
  struct sigaction sa;
  memset(&sa, 0, sizeof(sa));
  sa.sa_handler = segv_handler;
  sigaction(SIGSEGV, &sa, NULL);
  sigaction(SIGBUS, &sa, NULL);
}

static void use_temporary_dir(void)
{
  char tmpdir_template[] = "./syzkaller.XXXXXX";
  char* tmpdir = mkdtemp(tmpdir_template);
  if (!tmpdir) return;
  if (chmod(tmpdir, 0777)) {}
  if (chdir(tmpdir)) {}
}

static void sandbox_setuid(void)
{
  if (setgid(65534)) {}
  if (setuid(65534)) {}
}

struct csum_inet {
  uint32_t acc;
};
static void csum_inet_init(struct csum_inet* csum) { csum->acc = 0; }
static void csum_inet_update(struct csum_inet* csum, const uint8_t* data,
                             size_t length)
{
  if (length == 0) return;
  size_t i;
  for (i = 0; i < length - 1; i += 2)
    csum->acc += *(uint16_t*)&data[i];
  if (length & 1) csum->acc += (uint16_t)data[length - 1];
  while (csum->acc > 0xffff)
    csum->acc = (csum->acc & 0xffff) + (csum->acc >> 16);
}
static uint16_t csum_inet_digest(struct csum_inet* csum)
{
  return ~csum->acc;
}

static void inject_fault(int nth)
{
  // fail-nth via procfs when available (reference:
  // executor/common_linux.h fault injection setup)
  int fd = open("/proc/thread-self/fail-nth", O_RDWR);
  if (fd < 0) return;
  char buf[16];
  snprintf(buf, sizeof(buf), "%d", nth + 1);
  if (write(fd, buf, strlen(buf))) {}
  close(fd);
}"""

_LINUX_BACKEND = r"""// direct syscall backend"""

_SIM_BACKEND = r"""// hermetic test-target backend: calls are logged no-ops so the
// reproducer structure (copyins, dataflow, options) stays verifiable
static intptr_t sim_call(intptr_t nr, ...)
{
  return nr >= 0 ? 0 : -1;
}"""
